"""Scale validation at the reference's design target: O(100) concurrent
jobs per single controller process (reference tf_job_design_doc.md:32-36;
load-gen parity hack/genjob/genjob.go:30-92).

100 TPUJobs are driven through a real TPUJobController against the
in-memory cluster with a fake kubelet (pods advance Pending → Running →
Succeeded with exit 0; no real processes). Asserts the controller keeps up:
every job reaches Succeeded, the workqueue drains, no expectation is left
wedged, and p99 sync latency stays bounded.
"""

import threading
import time

import pytest

from tf_operator_tpu.api import constants
from tf_operator_tpu.cli.genjob import synthetic_job
from tf_operator_tpu.controller.jobcontroller import JobControllerConfig
from tf_operator_tpu.controller import tpujob_controller as tc_mod
from tf_operator_tpu.controller.tpujob_controller import TPUJobController
from tf_operator_tpu.runtime import objects
from tf_operator_tpu.runtime.memcluster import InMemoryCluster

NUM_JOBS = 100
WORKERS_PER_JOB = 2


class FakeKubelet(threading.Thread):
    """Advances every pod Pending → Running → (next pass) Succeeded."""

    def __init__(self, client: InMemoryCluster, stop: threading.Event) -> None:
        super().__init__(daemon=True)
        self.client = client
        self.stop_event = stop
        self.seen_running: set[str] = set()

    def run(self) -> None:
        while not self.stop_event.is_set():
            for pod in list(self.client.list(objects.PODS, "default")):
                name = objects.name_of(pod)
                phase = objects.pod_phase(pod)
                try:
                    if phase == objects.PENDING:
                        objects.set_pod_phase(pod, objects.RUNNING)
                        self.client.update_status(objects.PODS, pod)
                    elif phase == objects.RUNNING:
                        if name in self.seen_running:
                            objects.set_pod_phase(pod, objects.SUCCEEDED)
                            objects.set_container_terminated(
                                pod, constants.DEFAULT_CONTAINER_NAME, 0
                            )
                            self.client.update_status(objects.PODS, pod)
                        else:
                            self.seen_running.add(name)
                except Exception:
                    # Conflict with a concurrent controller write: the next
                    # pass re-reads and retries — exactly a kubelet's model.
                    continue
            time.sleep(0.05)


@pytest.mark.slow
def test_hundred_concurrent_jobs_all_succeed():
    client = InMemoryCluster()
    controller = TPUJobController(
        client,
        JobControllerConfig(
            reconcile_period=0.5, informer_resync=1.0, threadiness=4
        ),
    )
    stop = threading.Event()
    # Window the process-global sync histogram to THIS test's observations
    # (earlier tests in the same pytest process share the registry).
    sync_baseline = tc_mod.SYNC_SECONDS.snapshot()
    threading.Thread(target=controller.run, args=(stop,), daemon=True).start()
    kubelet = FakeKubelet(client, stop)
    kubelet.start()
    try:
        t0 = time.monotonic()
        for i in range(NUM_JOBS):
            client.create(
                objects.TPUJOBS,
                synthetic_job(f"scale-{i}", "default", WORKERS_PER_JOB, None, None),
            )
        submit_dt = time.monotonic() - t0

        def succeeded_count() -> int:
            n = 0
            for job in client.list(objects.TPUJOBS, "default"):
                for cond in job.get("status", {}).get("conditions", []):
                    if cond["type"] == "Succeeded" and cond["status"] == "True":
                        n += 1
                        break
            return n

        deadline = time.monotonic() + 120
        done = 0
        while time.monotonic() < deadline:
            done = succeeded_count()
            if done == NUM_JOBS:
                break
            time.sleep(0.5)
        total_dt = time.monotonic() - t0
        assert done == NUM_JOBS, f"only {done}/{NUM_JOBS} jobs Succeeded"

        # The queue must fully drain once the fleet is terminal. The 1s
        # informer resync re-enqueues keys periodically, so poll for a
        # moment where the queue is empty rather than snapshotting once.
        drain_deadline = time.monotonic() + 10
        drained = False
        while time.monotonic() < drain_deadline:
            if len(controller.queue) == 0:
                drained = True
                break
            time.sleep(0.05)
        assert drained, f"workqueue never drained ({len(controller.queue)} keys)"

        # Zero wedged expectations: every outstanding key is satisfied.
        exp = controller.expectations
        wedged = [k for k in list(exp._store) if not exp.satisfied(k)]
        assert not wedged, f"wedged expectations: {wedged}"

        # p99 sync latency bounded: generous bound (shared CI machine), the
        # point is no pathological syncs (reference budget: a 15s resync
        # loop must not back up — jobcontroller.go:49-55).
        p99 = tc_mod.SYNC_SECONDS.quantile(0.99, since=sync_baseline)
        assert p99 <= 2.5, f"p99 sync latency {p99}s"

        pods = client.list(objects.PODS, "default")
        print(
            f"\nscale: {NUM_JOBS} jobs x {WORKERS_PER_JOB} workers "
            f"submit={submit_dt:.2f}s all-succeeded={total_dt:.1f}s "
            f"p99-sync={p99 * 1e3:.0f}ms pods={len(pods)}"
        )
    finally:
        stop.set()
        time.sleep(0.3)
