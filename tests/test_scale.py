"""Scale validation, now at 10x the reference's design target.

The reference pins O(100) concurrent jobs per controller process
(tf_job_design_doc.md:32-36; load-gen parity hack/genjob/genjob.go:30-92).
After the indexed-informer/cached-read work (ISSUE 3) the same controller
sustains 1000 jobs: every sync's pod/service read is an index lookup and
steady-state reconcile waves issue zero API `list` calls for pods,
services, or nodes — asserted here against the tpu_api_requests_total
counters, not inferred.

Both tests drive a real TPUJobController + InMemoryCluster through
tools/bench_control_plane.py's harness (watch-driven fake kubelet — it
never lists, so the list counters measure only the control plane):

- tier-1 keeps a 100-job smoke (the reference's design target, now fast
  enough to run on every commit);
- the 1000-job benchmark is `slow` + `scale` (the judge-runnable scale
  tier; also emitted by bench.py as a BENCH line).
"""

import pytest

from tools.bench_control_plane import run_bench


def _assert_healthy(result: dict, jobs: int, p99_ms: float) -> None:
    assert "error" not in result, result
    assert result["succeeded"] == jobs, result
    # Zero wedged expectations: every outstanding key is satisfied.
    assert result["wedged_expectations"] == [], result
    # Steady-state reconcile waves are cache-served end to end: not one
    # API list for pods/services/nodes while the fleet idled at Running.
    assert result["steady_list_calls"] == {
        "pods": 0, "services": 0, "nodes": 0
    }, result
    # Reconcile waves DID run during the window (the zero above is not a
    # parked controller).
    assert result["steady_syncs"] > 0, result
    # The workqueue drains once the fleet is terminal — guards the new
    # delayed-heap coalescing against leaking ready keys forever.
    assert result["queue_drained"], result
    # p99 sync latency bounded: generous (shared CI machine); the point is
    # no pathological syncs (reference budget: the resync loop must not
    # back up — jobcontroller.go:49-55).
    assert result["p99_sync_ms"] <= p99_ms, result


@pytest.mark.scale
def test_hundred_job_smoke_zero_list_steady_state():
    """The reference's O(100) design target as a tier-1 smoke."""
    result = run_bench(
        jobs=100, workers=1, threadiness=4,
        reconcile_period=0.5, steady_seconds=2.0, timeout=120.0,
    )
    _assert_healthy(result, 100, p99_ms=2500.0)


@pytest.mark.slow
@pytest.mark.scale
def test_thousand_concurrent_jobs_all_succeed():
    """10x the design target: 1000 jobs, bounded p99, cache-served reads."""
    result = run_bench(
        jobs=1000, workers=1, threadiness=4,
        reconcile_period=2.0, steady_seconds=6.0, timeout=300.0,
    )
    _assert_healthy(result, 1000, p99_ms=2500.0)
    # Whole-run list traffic for pods/services/nodes is O(1), not O(jobs):
    # the pre-index controller issued one namespace LIST per release call
    # (>= 1 per job). A small allowance remains because the same-pass gang
    # release deliberately keeps an API fallback for the few-ms window
    # before the pod ADDED deltas land in the cache (core.py
    # _list_gang_pods) — on a starved CI machine a handful of releases can
    # lose that race; steady state (asserted above) is always zero.
    whole_run = result["api_requests"].get("list", {})
    total_lists = sum(whole_run.get(k, 0) for k in ("pods", "services", "nodes"))
    assert total_lists <= 10, result
