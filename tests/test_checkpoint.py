"""Checkpoint/resume unit tests (orbax-backed, sharded state on the mesh)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tf_operator_tpu.models.mnist import MnistCNN
from tf_operator_tpu.parallel.mesh import create_mesh
from tf_operator_tpu.parallel.sharding import replicate
from tf_operator_tpu.train.checkpoint import CheckpointManager
from tf_operator_tpu.train.steps import TrainState, sgd_momentum


def _state(mesh):
    model = MnistCNN()
    x = jnp.zeros((4, 28, 28, 1), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=True)
    state = TrainState.create(variables["params"], sgd_momentum(0.1))
    return replicate(mesh, state)


def test_save_restore_roundtrip_preserves_values_and_sharding(tmp_path):
    mesh = create_mesh({"dp": 8})
    state = _state(mesh)
    with CheckpointManager(str(tmp_path / "ckpt")) as mgr:
        assert mgr.latest_step() is None
        mgr.save(7, state)
        mgr.wait()
        assert mgr.latest_step() == 7

        target = _state(mesh)  # fresh init: different RNG-free but same shape
        restored = mgr.restore(None, target)

    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        restored.params, state.params,
    )
    # restored arrays carry the target's NamedShardings (land on the mesh)
    leaf = jax.tree.leaves(restored.params)[0]
    assert leaf.sharding.mesh.shape == mesh.shape


def test_restore_or_init(tmp_path):
    mesh = create_mesh({"dp": 8})
    state = _state(mesh)
    with CheckpointManager(str(tmp_path / "c")) as mgr:
        out, start = mgr.restore_or_init(state)
        assert start == 0 and out is state

        bumped = state.replace(step=state.step + 5)
        mgr.save(4, bumped)
        mgr.wait()
        resumed, start = mgr.restore_or_init(state)
        assert start == 5
        assert int(resumed.step) == 5


def test_max_to_keep_garbage_collects(tmp_path):
    mesh = create_mesh({"dp": 8})
    state = _state(mesh)
    d = tmp_path / "gc"
    with CheckpointManager(str(d), max_to_keep=2) as mgr:
        for s in range(5):
            mgr.save(s, state)
        mgr.wait()
        assert mgr.latest_step() == 4
    kept = {int(p) for p in os.listdir(d) if p.isdigit()}
    assert kept == {3, 4}


def test_restore_missing_raises(tmp_path):
    mesh = create_mesh({"dp": 8})
    with CheckpointManager(str(tmp_path / "empty")) as mgr:
        with pytest.raises(FileNotFoundError):
            mgr.restore(None, _state(mesh))


def test_checkpoint_is_topology_portable(tmp_path):
    """A checkpoint written under one mesh restores under a DIFFERENT mesh
    and sharding strategy (elastic resume: e.g. a preempted dp-8 job
    resuming on dp-2 x fsdp-4): orbax reshards to the target's
    NamedShardings, values bit-identical."""
    from jax.sharding import PartitionSpec as P

    from tf_operator_tpu.models.mnist import MnistCNN
    from tf_operator_tpu.parallel.sharding import replicate, shard_params_fsdp
    from tf_operator_tpu.train.steps import adamw

    model = MnistCNN(dtype=jnp.float32)
    x = jnp.zeros((4, 28, 28, 1), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x, train=True)["params"]
    tx = adamw(1e-3)

    # Writer topology: dp-8, fully replicated state.
    dp_mesh = create_mesh({"dp": 8})
    writer = replicate(dp_mesh, TrainState.create(params, tx))
    path = str(tmp_path / "ckpt")
    with CheckpointManager(path) as mgr:
        mgr.save(7, writer)
        mgr.wait()

    # Reader topology: dp-2 x fsdp-4, params + moments fsdp-sharded.
    zmesh = create_mesh({"dp": 2, "fsdp": 4})
    target = TrainState.create(shard_params_fsdp(zmesh, params, min_size=64), tx)
    with CheckpointManager(path) as mgr:
        restored = mgr.restore(None, target)

    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        restored.params, writer.params,
    )
    k = restored.params["Dense_0"]["kernel"]
    assert k.sharding.mesh.shape == {"dp": 2, "fsdp": 4}
    assert k.sharding.spec == P("fsdp", None)
    assert k.addressable_shards[0].data.shape[0] == k.shape[0] // 4


def test_fsdp_state_roundtrip_preserves_shard_placement(tmp_path):
    """Save/restore of an FSDP-sharded TrainState (params AND adamw moments
    on P('fsdp')) must restore onto the same sharded placement — a resumed
    job re-gathering full params per chip would silently undo the memory
    sharding FSDP exists for."""
    from jax.sharding import PartitionSpec as P

    from tf_operator_tpu.models.mnist import MnistCNN
    from tf_operator_tpu.parallel.sharding import shard_params_fsdp
    from tf_operator_tpu.train.steps import adamw

    mesh = create_mesh({"fsdp": 8})
    model = MnistCNN(dtype=jnp.float32)
    x = jnp.zeros((4, 28, 28, 1), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x, train=True)["params"]
    tx = adamw(1e-3)

    def fsdp_state():
        return TrainState.create(shard_params_fsdp(mesh, params, min_size=64), tx)

    state = fsdp_state()
    with CheckpointManager(str(tmp_path / "ckpt")) as mgr:
        mgr.save(3, state)
        mgr.wait()
        restored = mgr.restore(None, fsdp_state())

    # values identical...
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        restored.params, state.params,
    )
    # ...and placement still sharded, for params and optimizer moments alike
    k = restored.params["Dense_0"]["kernel"]
    assert k.sharding.spec == P("fsdp", None)
    assert k.addressable_shards[0].data.shape[0] == k.shape[0] // 8
    mu = restored.opt_state[0].mu["Dense_0"]["kernel"]
    assert mu.sharding.spec == P("fsdp", None)
