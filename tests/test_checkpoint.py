"""Checkpoint/resume unit tests (orbax-backed, sharded state on the mesh)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tf_operator_tpu.models.mnist import MnistCNN
from tf_operator_tpu.parallel.mesh import create_mesh
from tf_operator_tpu.parallel.sharding import replicate
from tf_operator_tpu.train.checkpoint import CheckpointManager
from tf_operator_tpu.train.steps import TrainState, sgd_momentum


def _state(mesh):
    model = MnistCNN()
    x = jnp.zeros((4, 28, 28, 1), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=True)
    state = TrainState.create(variables["params"], sgd_momentum(0.1))
    return replicate(mesh, state)


def test_save_restore_roundtrip_preserves_values_and_sharding(tmp_path):
    mesh = create_mesh({"dp": 8})
    state = _state(mesh)
    with CheckpointManager(str(tmp_path / "ckpt")) as mgr:
        assert mgr.latest_step() is None
        mgr.save(7, state)
        mgr.wait()
        assert mgr.latest_step() == 7

        target = _state(mesh)  # fresh init: different RNG-free but same shape
        restored = mgr.restore(None, target)

    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        restored.params, state.params,
    )
    # restored arrays carry the target's NamedShardings (land on the mesh)
    leaf = jax.tree.leaves(restored.params)[0]
    assert leaf.sharding.mesh.shape == mesh.shape


def test_restore_or_init(tmp_path):
    mesh = create_mesh({"dp": 8})
    state = _state(mesh)
    with CheckpointManager(str(tmp_path / "c")) as mgr:
        out, start = mgr.restore_or_init(state)
        assert start == 0 and out is state

        bumped = state.replace(step=state.step + 5)
        mgr.save(4, bumped)
        mgr.wait()
        resumed, start = mgr.restore_or_init(state)
        assert start == 5
        assert int(resumed.step) == 5


def test_max_to_keep_garbage_collects(tmp_path):
    mesh = create_mesh({"dp": 8})
    state = _state(mesh)
    d = tmp_path / "gc"
    with CheckpointManager(str(d), max_to_keep=2) as mgr:
        for s in range(5):
            mgr.save(s, state)
        mgr.wait()
        assert mgr.latest_step() == 4
    kept = {int(p) for p in os.listdir(d) if p.isdigit()}
    assert kept == {3, 4}


def test_restore_missing_raises(tmp_path):
    mesh = create_mesh({"dp": 8})
    with CheckpointManager(str(tmp_path / "empty")) as mgr:
        with pytest.raises(FileNotFoundError):
            mgr.restore(None, _state(mesh))
