"""Metrics registry, tracer, and the live /metrics + /debug/traces endpoints.

The reference has neither metrics nor tracing (SURVEY.md §5) — these are
capability additions; the E2E asserts a real operator process serves both
and that reconcile activity shows up in the scrape."""

import json
import urllib.request

import pytest

from tf_operator_tpu.runtime.metrics import Registry
from tf_operator_tpu.runtime.tracing import Tracer

# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------


def test_counter_and_gauge_render():
    reg = Registry()
    c = reg.counter("requests_total", "Requests", ("method",))
    c.inc(method="GET")
    c.inc(2, method="POST")
    g = reg.gauge("depth", "Queue depth")
    g.set(7)
    g.inc()
    g.dec(3)
    text = reg.render()
    assert '# TYPE requests_total counter' in text
    assert 'requests_total{method="GET"} 1' in text
    assert 'requests_total{method="POST"} 2' in text
    assert "depth 5" in text
    assert c.value(method="GET") == 1


def test_counter_rejects_negative_and_wrong_labels():
    reg = Registry()
    c = reg.counter("x_total", labelnames=("a",))
    with pytest.raises(ValueError):
        c.inc(-1, a="v")
    with pytest.raises(ValueError):
        c.inc(b="v")


def test_histogram_buckets_cumulative():
    reg = Registry()
    h = reg.histogram("lat_seconds", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.1, 0.5, 5.0, 100.0):
        h.observe(v)
    text = reg.render()
    assert 'lat_seconds_bucket{le="0.1"} 2' in text  # 0.05, 0.1 (le inclusive)
    assert 'lat_seconds_bucket{le="1"} 3' in text
    assert 'lat_seconds_bucket{le="10"} 4' in text
    assert 'lat_seconds_bucket{le="+Inf"} 5' in text
    assert "lat_seconds_count 5" in text
    assert "lat_seconds_sum 105.65" in text


def test_histogram_quantile():
    reg = Registry()
    h = reg.histogram("q_seconds", labelnames=("op",), buckets=(0.1, 1.0, 10.0))
    assert h.quantile(0.99) == 0.0  # no observations
    for _ in range(99):
        h.observe(0.05, op="fast")
    h.observe(5.0, op="slow")
    # 99th of 100 observations is still in the first bucket
    assert h.quantile(0.5) == 0.1
    assert h.quantile(0.99) == 0.1
    assert h.quantile(1.0) == 10.0  # the slow one, merged across series
    assert h.quantile(1.0, op="fast") == 0.1  # single-series view
    h.observe(100.0, op="slow")
    assert h.quantile(1.0) == float("inf")  # overflow bucket


def test_registry_dedupes_families():
    reg = Registry()
    a = reg.counter("same_total")
    b = reg.counter("same_total")
    assert a is b
    with pytest.raises(ValueError):
        reg.gauge("same_total")


def test_registry_rejects_shape_mismatch():
    reg = Registry()
    reg.counter("c_total", labelnames=("a",))
    with pytest.raises(ValueError, match="labels"):
        reg.counter("c_total")
    reg.histogram("h_seconds", buckets=(1.0, 2.0))
    with pytest.raises(ValueError, match="buckets"):
        reg.histogram("h_seconds", buckets=(5.0,))


# ---------------------------------------------------------------------------
# tracing
# ---------------------------------------------------------------------------


def test_tracer_records_spans_and_exports_chrome_json():
    tr = Tracer(capacity=4)
    with tr.span("outer", job="ns/j"):
        with tr.span("inner"):
            pass
    spans = tr.spans()
    assert [s.name for s in spans] == ["inner", "outer"]  # close order
    assert spans[1].duration_us >= spans[0].duration_us
    assert spans[1].attrs == {"job": "ns/j"}

    doc = json.loads(tr.export_chrome_trace())
    names = {e["name"] for e in doc["traceEvents"]}
    assert {"inner", "outer"} <= names
    complete = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert all("ts" in e and "dur" in e for e in complete)


def test_tracer_ring_bounded_and_disable():
    tr = Tracer(capacity=2)
    for i in range(5):
        with tr.span(f"s{i}"):
            pass
    assert [s.name for s in tr.spans()] == ["s3", "s4"]
    tr.enabled = False
    with tr.span("hidden"):
        pass
    assert len(tr.spans()) == 2


def test_tracer_counts_ring_evictions():
    from tf_operator_tpu.runtime.metrics import TRACE_SPANS_DROPPED

    tr = Tracer(capacity=3, process_name="drop-probe")
    before = TRACE_SPANS_DROPPED.value(tracer="drop-probe")
    for i in range(5):
        with tr.span(f"s{i}"):
            pass
    assert tr.dropped == 2  # 5 appends into a 3-slot ring
    assert TRACE_SPANS_DROPPED.value(tracer="drop-probe") - before == 2
    assert tr.export_doc()["droppedSpans"] == 2
    tr.clear()
    assert tr.dropped == 0


def test_tracer_set_capacity_resizes_and_zero_disables():
    tr = Tracer(capacity=8)
    for i in range(4):
        with tr.span(f"s{i}"):
            pass
    tr.set_capacity(2)  # newest survive the shrink
    assert [s.name for s in tr.spans()] == ["s2", "s3"]
    assert tr.capacity == 2
    tr.set_capacity(0)
    assert not tr.enabled
    with tr.span("hidden"):
        pass
    tr.record("hidden2", 0.0, 1.0)
    assert tr.spans() == []
    tr.set_capacity(16)
    assert tr.enabled and tr.capacity == 16


def test_tracer_record_explicit_stamps_and_ordering():
    import time as _time

    tr = Tracer(capacity=8)
    t0 = _time.monotonic()
    tr.record("later", t0 + 0.5, t0 + 0.6, request_id="r1")
    tr.record("earlier", t0 + 0.1, t0 + 0.2, request_id="r1")
    doc = tr.export_doc()
    spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    by_name = {e["name"]: e for e in spans}
    assert by_name["earlier"]["ts"] < by_name["later"]["ts"]
    assert abs(by_name["later"]["dur"] - 1e5) < 1e4  # ~100ms in us
    assert by_name["later"]["args"]["request_id"] == "r1"
    # End-before-start clamps to zero rather than exporting negative dur.
    tr.record("clamped", t0 + 1.0, t0 + 0.5)
    assert tr.spans("clamped")[0].duration_us == 0.0


def test_tracer_sanitizes_weird_attr_values():
    tr = Tracer(capacity=4)
    evil = "tok\x00en\nnew\ud800line" + "x" * 1000
    with tr.span("prompt", text=evil, n=7):
        pass
    doc_str = tr.export_chrome_trace()
    doc = json.loads(doc_str)  # never corrupted
    args = [e for e in doc["traceEvents"] if e["ph"] == "X"][0]["args"]
    assert "\x00" not in args["text"] and "\n" not in args["text"]
    assert "\ud800" not in args["text"]
    assert len(args["text"]) <= 256 + 3
    assert args["n"] == "7"
    # The raw export string is strict-JSON safe (no lone surrogates).
    doc_str.encode("utf-8")


def test_merge_chrome_traces_rebases_dedupes_and_labels_pids():
    from tf_operator_tpu.runtime.tracing import merge_chrome_traces

    a, b = Tracer(process_name="router"), Tracer(process_name="replica")
    # Pretend b's process started 1s later on the wall clock.
    b._epoch_unix = a._epoch_unix + 1.0
    a.record("router.dispatch", a._epoch + 0.010, a._epoch + 0.020,
             request_id="req1")
    b.record("replica.request", b._epoch + 0.012, b._epoch + 0.018,
             request_id="req1")
    merged = merge_chrome_traces([
        ("router", a.export_doc()),
        ("replica:r0", b.export_doc()),
        ("replica:r1", b.export_doc()),  # same ring fetched twice
    ])
    spans = [e for e in merged["traceEvents"] if e["ph"] == "X"]
    assert len(spans) == 2  # the duplicate fetch deduped
    by_name = {e["name"]: e for e in spans}
    # b's span rebased +1s onto a's epoch: it lands AFTER a's, on its
    # own pid.
    assert by_name["replica.request"]["ts"] > by_name[
        "router.dispatch"]["ts"]
    assert by_name["replica.request"]["pid"] != by_name[
        "router.dispatch"]["pid"]
    assert all(e["args"]["request_id"] == "req1" for e in spans)
    names = {e["args"]["name"] for e in merged["traceEvents"]
             if e["ph"] == "M"}
    assert {"router", "replica:r0", "replica:r1"} <= names
    assert merge_chrome_traces([])["traceEvents"] == []


# ---------------------------------------------------------------------------
# live endpoints on a real operator process
# ---------------------------------------------------------------------------


def test_operator_serves_metrics_and_traces(operator):
    text = urllib.request.urlopen(operator + "/metrics", timeout=5).read().decode()
    assert "# TYPE tpu_operator_syncs_total counter" in text

    doc = json.loads(
        urllib.request.urlopen(operator + "/debug/traces", timeout=5).read()
    )
    assert any(e.get("name") == "process_name" for e in doc["traceEvents"])


def test_metrics_not_shadowed_by_dashboard_spa_fallback():
    """With the dashboard mounted, /metrics must still serve Prometheus text
    (the SPA fallback swallows unmatched GETs, so mount order matters)."""
    from tf_operator_tpu.dashboard.backend import mount_dashboard
    from tf_operator_tpu.runtime.apiserver import ApiServer
    from tf_operator_tpu.runtime.memcluster import InMemoryCluster
    from tf_operator_tpu.runtime.metrics import REGISTRY
    from tf_operator_tpu.runtime.observability import mount_observability

    REGISTRY.counter("spa_fallback_probe_total", "test probe")
    server = ApiServer(InMemoryCluster())
    mount_observability(server)
    mount_dashboard(server, InMemoryCluster())
    server.start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        resp = urllib.request.urlopen(base + "/metrics", timeout=5)
        assert resp.headers["Content-Type"].startswith("text/plain")
        assert b"# TYPE" in resp.read()
        # and the dashboard still serves its app shell
        html = urllib.request.urlopen(base + "/", timeout=5).read()
        assert b"<" in html
    finally:
        server.stop()
