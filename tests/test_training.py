"""Training-stack tests on the CPU mesh: models learn, steps jit cleanly
under dp / dp+tp+sp shardings, the distributed-env contract parses."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tf_operator_tpu.models.mnist import MnistCNN
from tf_operator_tpu.models.resnet import resnet18, resnet50
from tf_operator_tpu.models.transformer import (
    Transformer,
    TransformerConfig,
    param_sharding_rules,
    quantize_decode_params,
)
from tf_operator_tpu.parallel.mesh import create_mesh
from tf_operator_tpu.parallel.sharding import replicate, shard_batch, shard_params_by_rules
from tf_operator_tpu.train import data as data_lib
from tf_operator_tpu.train import distributed
from tf_operator_tpu.train.steps import (
    TrainState,
    adamw,
    make_classifier_train_step,
    make_lm_train_step,
    sgd_momentum,
)

# Real training loops with CPU-mesh jit compiles: minutes each on a
# loaded host.
pytestmark = pytest.mark.slow


class TestMnistTraining:
    def test_loss_decreases_dp(self):
        mesh = create_mesh({"dp": 8})
        model = MnistCNN(dtype=jnp.float32)
        it = data_lib.synthetic_mnist(64)
        batch0 = next(it)
        variables = model.init(jax.random.PRNGKey(0), batch0["image"], train=True)
        tx = sgd_momentum(0.05)
        state = TrainState.create(variables["params"], tx)
        state = replicate(mesh, state)
        step = make_classifier_train_step(model, tx, mesh, has_batch_stats=False)
        losses = []
        for _ in range(30):
            batch = shard_batch(mesh, next(it))
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0] * 0.7, losses[::10]


class TestResNet:
    def test_resnet50_forward_shape(self):
        model = resnet50(dtype=jnp.float32)
        x = jnp.ones((2, 64, 64, 3))
        variables = model.init(jax.random.PRNGKey(0), x, train=False)
        out = model.apply(variables, x, train=False)
        assert out.shape == (2, 1000)
        n_params = sum(p.size for p in jax.tree.leaves(variables["params"]))
        # ResNet-50 has ~25.6M params.
        assert 25_000_000 < n_params < 26_000_000, n_params

    def test_s2d_stem_is_exact_rewrite_of_conv7(self):
        """The s2d stem computes the SAME function as the 7x7/s2 stem when
        its 4x4x12 kernel is the embedding of the 7x7x3 one."""
        from tf_operator_tpu.models.resnet import space_to_depth, stem_kernel_to_s2d

        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(2, 32, 32, 3)), jnp.float32)
        k7 = rng.normal(size=(7, 7, 3, 8)).astype(np.float32) * 0.1

        direct = jax.lax.conv_general_dilated(
            x, jnp.asarray(k7), window_strides=(2, 2),
            padding=[(3, 3), (3, 3)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        rewritten = jax.lax.conv_general_dilated(
            space_to_depth(x, 2), jnp.asarray(stem_kernel_to_s2d(k7)),
            window_strides=(1, 1), padding=[(2, 1), (2, 1)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        assert direct.shape == rewritten.shape == (2, 16, 16, 8)
        np.testing.assert_allclose(direct, rewritten, rtol=1e-5, atol=1e-5)

    def test_s2d_resnet_trains_and_matches_shapes(self):
        model = resnet50(num_classes=10, dtype=jnp.float32, stem="s2d")
        x = jnp.ones((2, 64, 64, 3))
        variables = model.init(jax.random.PRNGKey(0), x, train=False)
        out = model.apply(variables, x, train=False)
        assert out.shape == (2, 10)
        k = variables["params"]["stem_s2d"]["kernel"]
        assert k.shape == (4, 4, 12, 64)

    def test_resnet18_train_step_dp(self):
        mesh = create_mesh({"dp": 8})
        model = resnet18(num_classes=10, dtype=jnp.float32)
        x = jnp.ones((8, 32, 32, 3))
        variables = model.init(jax.random.PRNGKey(0), x, train=True)
        tx = sgd_momentum(0.01)
        state = TrainState.create(
            variables["params"], tx, batch_stats=variables["batch_stats"]
        )
        state = replicate(mesh, state)
        step = make_classifier_train_step(model, tx, mesh, has_batch_stats=True)
        batch = shard_batch(
            mesh,
            {
                "image": np.random.default_rng(0).normal(size=(8, 32, 32, 3)).astype(np.float32),
                "label": np.zeros((8,), np.int32),
            },
        )
        state, metrics = step(state, batch)
        assert np.isfinite(float(metrics["loss"]))
        assert int(state.step) == 1


class TestTransformer:
    def _mesh_cfg(self, mesh):
        return TransformerConfig(
            vocab_size=256,
            d_model=64,
            n_heads=4,
            n_layers=2,
            d_ff=128,
            max_seq_len=64,
            dtype=jnp.float32,
            mesh=mesh,
        )

    def test_lm_step_dp_tp_sp(self):
        mesh = create_mesh({"dp": 2, "sp": 2, "tp": 2})
        cfg = self._mesh_cfg(mesh)
        model = Transformer(cfg)
        it = data_lib.synthetic_tokens(4, 32, vocab_size=cfg.vocab_size)
        batch0 = next(it)
        params = model.init(jax.random.PRNGKey(0), batch0["tokens"])["params"]
        params = shard_params_by_rules(mesh, params, param_sharding_rules())
        tx = adamw(1e-3)
        state = TrainState.create(params, tx)
        step = make_lm_train_step(model, tx, mesh)
        losses = []
        for _ in range(5):
            batch = next(it)
            batch = {
                "tokens": jnp.asarray(batch["tokens"]),
                "targets": jnp.asarray(batch["targets"]),
            }
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0]  # adam on random tokens still memorizes a bit

    def test_ulysses_impl_matches_dense_model(self):
        """ring_impl='ulysses': the all-to-all sequence-parallel path
        (parallel/ulysses.py) reproduces the dense model exactly, like
        the ring impls."""
        import dataclasses

        mesh = create_mesh({"sp": 4}, devices=jax.devices()[:4])
        cfg_u = dataclasses.replace(
            self._mesh_cfg(mesh), ring_impl="ulysses"
        )
        cfg_dense = TransformerConfig(
            vocab_size=256, d_model=64, n_heads=4, n_layers=2, d_ff=128,
            max_seq_len=64, dtype=jnp.float32, mesh=None,
        )
        tokens = jnp.asarray(
            np.random.default_rng(5).integers(0, 256, size=(2, 32)), jnp.int32
        )
        params = Transformer(cfg_dense).init(jax.random.PRNGKey(0), tokens)["params"]
        out_dense = Transformer(cfg_dense).apply({"params": params}, tokens)
        out_u = Transformer(cfg_u).apply({"params": params}, tokens)
        assert float(jnp.abs(out_dense - out_u).max()) < 1e-4

    def test_ring_matches_dense_model(self):
        """Same params, sp=4 ring attention vs single-device dense attention."""
        mesh = create_mesh({"sp": 4}, devices=jax.devices()[:4])
        cfg_ring = self._mesh_cfg(mesh)
        cfg_dense = TransformerConfig(
            vocab_size=256, d_model=64, n_heads=4, n_layers=2, d_ff=128,
            max_seq_len=64, dtype=jnp.float32, mesh=None,
        )
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, 256, size=(2, 32)), jnp.int32
        )
        params = Transformer(cfg_dense).init(jax.random.PRNGKey(0), tokens)["params"]
        out_dense = Transformer(cfg_dense).apply({"params": params}, tokens)
        out_ring = Transformer(cfg_ring).apply({"params": params}, tokens)
        assert float(jnp.abs(out_dense - out_ring).max()) < 1e-4


    def test_ring_impl_flash_matches_stream_in_model(self):
        """The custom-VJP ring ('flash' impl) trains identically to the
        autodiff ring in a full LM step (loss + gradients agree)."""
        import dataclasses

        mesh = create_mesh({"sp": 4}, devices=jax.devices()[:4])
        cfg_stream = dataclasses.replace(
            self._mesh_cfg(mesh), ring_impl="stream"
        )
        cfg_flash = dataclasses.replace(
            self._mesh_cfg(mesh), ring_impl="flash"
        )
        tokens = jnp.asarray(
            np.random.default_rng(3).integers(0, 256, size=(2, 32)), jnp.int32
        )
        params = Transformer(cfg_stream).init(
            jax.random.PRNGKey(0), tokens
        )["params"]
        out_s = Transformer(cfg_stream).apply({"params": params}, tokens)
        out_f = Transformer(cfg_flash).apply({"params": params}, tokens)
        assert float(jnp.abs(out_s - out_f).max()) < 1e-4

        def loss_with(cfg):
            def fn(p):
                out = Transformer(cfg).apply({"params": p}, tokens)
                return (out.astype(jnp.float32) ** 2).mean()

            return fn

        g_s = jax.grad(loss_with(cfg_stream))(params)
        g_f = jax.grad(loss_with(cfg_flash))(params)
        diffs = jax.tree.map(
            lambda a, b: float(jnp.abs(a - b).max()), g_s, g_f
        )
        assert max(jax.tree.leaves(diffs)) < 1e-4, diffs

    def test_remat_is_numerically_identical(self):
        """remat=True must change memory behavior only: same forward logits
        and same gradients as the stored-activation model (jax.checkpoint
        recomputes, never approximates)."""
        import dataclasses

        cfg = TransformerConfig(
            vocab_size=256, d_model=64, n_heads=4, n_layers=2, d_ff=128,
            max_seq_len=64, dtype=jnp.float32, mesh=None,
        )
        cfg_remat = dataclasses.replace(cfg, remat=True)
        tokens = jnp.asarray(
            np.random.default_rng(1).integers(0, 256, size=(2, 32)), jnp.int32
        )
        targets = jnp.asarray(
            np.random.default_rng(2).integers(0, 256, size=(2, 32)), jnp.int32
        )
        params = Transformer(cfg).init(jax.random.PRNGKey(0), tokens)["params"]

        def loss_fn(model):
            def f(p):
                import optax

                logits = model.apply({"params": p}, tokens)
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits, targets
                ).mean()

            return f

        out = Transformer(cfg).apply({"params": params}, tokens)
        out_r = Transformer(cfg_remat).apply({"params": params}, tokens)
        assert float(jnp.abs(out - out_r).max()) < 1e-6

        g = jax.grad(loss_fn(Transformer(cfg)))(params)
        g_r = jax.grad(loss_fn(Transformer(cfg_remat)))(params)
        diffs = jax.tree.map(lambda a, b: float(jnp.abs(a - b).max()), g, g_r)
        assert max(jax.tree.leaves(diffs)) < 1e-6

        # and the remat boundary is really in the jaxpr (checkpoint primitive)
        jaxpr = jax.make_jaxpr(loss_fn(Transformer(cfg_remat)))(params)
        assert "remat" in str(jaxpr) or "checkpoint" in str(jaxpr)


class TestDistributedEnv:
    def test_from_tpu_env(self):
        env = {
            "TPU_COORDINATOR_ADDRESS": "job-worker-0:2222",
            "TPU_WORKER_ID": "2",
            "TPU_NUM_PROCESSES": "4",
            "TPU_WORKER_HOSTNAMES": "a,b,c,d",
            "TPU_ACCELERATOR_TYPE": "v5e-16",
            "TPU_TOPOLOGY": "4x4",
        }
        topo = distributed.from_env(env)
        assert topo.is_distributed
        assert topo.process_id == 2
        assert topo.num_processes == 4
        assert topo.worker_hostnames == ["a", "b", "c", "d"]

    def test_fallback_to_tf_config(self):
        env = {
            "TF_CONFIG": '{"cluster": {"worker": ["w0:2222", "w1:2222"]}, "task": {"type": "worker", "index": 1}}'
        }
        topo = distributed.from_env(env)
        assert topo.process_id == 1
        assert topo.num_processes == 2
        assert topo.coordinator_address == "w0:2222"

    def test_single_process(self):
        topo = distributed.from_env({})
        assert not topo.is_distributed
        assert distributed.initialize(topo) is topo  # no-op, no crash

    def test_evaluator_role_is_standalone(self):
        """An evaluator pod must NEVER join the worker rendezvous, no
        matter how many workers the cluster map lists (the operator
        excludes evaluators from the cluster — reference parity)."""
        env = {
            "TF_CONFIG": '{"cluster": {"worker": ["w0:2222", "w1:2222"]},'
                         ' "task": {"type": "evaluator", "index": 0}}'
        }
        topo = distributed.from_env(env)
        assert topo.role == "evaluator"
        assert not topo.is_distributed
        assert topo.num_processes == 1
        assert topo.coordinator_address is None
        # And role survives alongside the TPU env contract.
        worker = distributed.from_env(
            {"TPU_WORKER_ID": "1", "TPU_NUM_PROCESSES": "2",
             "TF_CONFIG": '{"task": {"type": "chief", "index": 0}}'}
        )
        assert worker.role == "chief"
        assert worker.process_id == 1  # TPU env wins for identity


def test_eval_step_exact_over_uneven_batches():
    """The Evaluator-side step: inference mode, exact aggregate metrics
    with tail batches NOT divisible by the data axis (padded + masked),
    one XLA compilation for all batch sizes, and agreement with a direct
    whole-dataset computation."""
    import pytest

    from tf_operator_tpu.train.steps import (
        TrainState,
        adamw,
        evaluate,
        make_classifier_eval_step,
    )

    mesh = create_mesh({"dp": 8})
    model = MnistCNN(dtype=jnp.float32)
    rng = np.random.default_rng(0)
    xs = rng.normal(size=(48, 28, 28, 1)).astype(np.float32)
    ys = rng.integers(0, 10, 48).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), jnp.asarray(xs[:8]), train=True)["params"]
    state = replicate(mesh, TrainState.create(params, adamw(1e-3)))
    eval_step = make_classifier_eval_step(model, mesh, has_batch_stats=False)

    def batches():
        # constant batch 16 with empty, 9- and 7-row tails (neither a
        # multiple of dp=8) — the cases the padding+mask design exists for.
        for lo, hi in ((0, 0), (0, 16), (16, 32), (32, 41), (41, 48)):
            yield {"image": xs[lo:hi], "label": ys[lo:hi]}

    metrics = evaluate(eval_step, state, batches())
    assert metrics["count"] == 48
    # one compiled executable despite different host batch sizes
    assert eval_step.compilation_count() in (1, -1)
    # oracle: single full-dataset forward
    logits = model.apply({"params": params}, jnp.asarray(xs), train=False)
    acc = float((jnp.argmax(logits, -1) == jnp.asarray(ys)).mean())
    assert metrics["accuracy"] == pytest.approx(acc, abs=1e-6)

    with pytest.raises(ValueError):
        evaluate(eval_step, state, [])


def test_chunked_xent_matches_naive():
    """chunked_lm_xent == naive full-logits loss, value AND gradients."""
    from tf_operator_tpu.train.steps import chunked_lm_xent, cross_entropy

    rng = np.random.default_rng(0)
    b, s, d, v = 2, 64, 16, 97
    hidden = jnp.asarray(rng.normal(size=(b, s, d)), jnp.float32)
    kernel = jnp.asarray(rng.normal(size=(d, v)) * 0.3, jnp.float32)
    bias = jnp.asarray(rng.normal(size=(v,)) * 0.1, jnp.float32)
    labels = jnp.asarray(rng.integers(0, v, (b, s)), jnp.int32)

    def naive(hidden, kernel, bias):
        return cross_entropy(hidden @ kernel + bias, labels)

    def chunked(hidden, kernel, bias):
        return chunked_lm_xent(hidden, kernel, bias, labels, chunk=16)

    ln, gn = jax.value_and_grad(naive, argnums=(0, 1, 2))(hidden, kernel, bias)
    lc, gc = jax.value_and_grad(chunked, argnums=(0, 1, 2))(hidden, kernel, bias)
    np.testing.assert_allclose(ln, lc, rtol=1e-6)
    for a, c in zip(gn, gc):
        np.testing.assert_allclose(a, c, rtol=1e-4, atol=1e-6)

    with np.testing.assert_raises(ValueError):
        chunked_lm_xent(hidden, kernel, bias, labels, chunk=48)


def test_lm_step_with_chunked_xent_matches_naive_step():
    """A full LM train step with xent_chunk produces the same loss and the
    same updated params as the materialized-logits step."""
    mesh = create_mesh({"dp": 1}, jax.devices("cpu")[:1])
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq_len=32, dtype=jnp.float32, mesh=mesh,
    )
    model = Transformer(cfg)
    tokens = jnp.zeros((2, 32), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    rng = np.random.default_rng(1)
    batch = {
        "tokens": jnp.asarray(rng.integers(0, 64, (2, 32)), jnp.int32),
        "targets": jnp.asarray(rng.integers(0, 64, (2, 32)), jnp.int32),
    }
    tx = adamw(1e-3)
    outs = []
    for chunk in (None, 8):
        state = TrainState.create(params, tx)
        step = make_lm_train_step(
            model, tx, mesh, seq_axis=None, donate=False, xent_chunk=chunk
        )
        state, metrics = step(state, batch)
        outs.append((float(metrics["loss"]), state.params))
    assert abs(outs[0][0] - outs[1][0]) < 1e-5, (outs[0][0], outs[1][0])
    for a, b in zip(jax.tree.leaves(outs[0][1]), jax.tree.leaves(outs[1][1])):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-6)


def test_warmup_cosine_schedule_in_train_step():
    """The schedule composes with adamw inside jit: LR warms up then
    decays, checkpoint-free (step lives in optimizer state)."""
    from tf_operator_tpu.train.steps import warmup_cosine

    sched = warmup_cosine(1e-2, total_steps=100, warmup_steps=10)
    assert float(sched(0)) == 0.0
    assert abs(float(sched(10)) - 1e-2) < 1e-8
    assert float(sched(100)) < float(sched(50)) < float(sched(10))
    assert abs(float(sched(100)) - 1e-3) < 1e-8  # end fraction 0.1

    mesh = create_mesh({"dp": 1}, jax.devices("cpu")[:1])
    cfg = TransformerConfig(
        vocab_size=32, d_model=16, n_heads=2, n_layers=1, d_ff=32,
        max_seq_len=16, dtype=jnp.float32,
    )
    model = Transformer(cfg)
    toks = jnp.zeros((2, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), toks)["params"]
    tx = adamw(warmup_cosine(5e-3, total_steps=20, warmup_steps=2))
    state = TrainState.create(params, tx)
    step = make_lm_train_step(model, tx, mesh, seq_axis=None, donate=False)
    before = jax.tree.leaves(state.params)[0]
    state, _ = step(state, {"tokens": toks, "targets": toks})
    # Step 0 has LR 0 (warmup start): params must be unchanged.
    np.testing.assert_array_equal(
        np.asarray(before), np.asarray(jax.tree.leaves(state.params)[0])
    )
    state, _ = step(state, {"tokens": toks, "targets": toks})
    assert not np.array_equal(
        np.asarray(before), np.asarray(jax.tree.leaves(state.params)[0])
    )


def test_lm_eval_exact_over_uneven_batches():
    """evaluate_lm pads uneven host batches to one shape, compiles once,
    and produces EXACTLY the naive full-logits mean token loss."""
    import optax

    from tf_operator_tpu.train.steps import evaluate_lm, make_lm_eval_step

    mesh = create_mesh({"dp": 4}, jax.devices()[:4])
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq_len=32, dtype=jnp.float32, mesh=None,
    )
    model = Transformer(cfg)
    rng = np.random.default_rng(0)
    all_toks = jnp.asarray(rng.integers(0, 64, (11, 24)), jnp.int32)
    all_targs = jnp.asarray(rng.integers(0, 64, (11, 24)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), all_toks[:1])["params"]
    state = TrainState.create(params, adamw(1e-3))

    # Naive reference: full logits, token-mean over ALL 11 rows.
    logits = model.apply({"params": params}, all_toks)
    per_tok = optax.softmax_cross_entropy_with_integer_labels(
        logits, all_targs
    )
    want = float(per_tok.mean())

    eval_step = make_lm_eval_step(model, mesh, xent_chunk=8)
    # Uneven batch sizes: 4 + 4 + 3 (tail padded), plus an empty one.
    batches = [
        {"tokens": all_toks[:4], "targets": all_targs[:4]},
        {"tokens": all_toks[4:8], "targets": all_targs[4:8]},
        {"tokens": all_toks[8:8], "targets": all_targs[8:8]},
        {"tokens": all_toks[8:], "targets": all_targs[8:]},
    ]
    out = evaluate_lm(eval_step, state, batches)
    assert out["tokens"] == 11 * 24
    np.testing.assert_allclose(out["loss"], want, rtol=1e-5)
    np.testing.assert_allclose(out["perplexity"], np.exp(want), rtol=1e-4)
    assert eval_step.compilation_count() in (-1, 1)


def test_sharded_xent_matches_naive():
    """Vocab-parallel + sequence-parallel chunked xent over a dp x sp x tp
    mesh == naive full-logits loss, value AND gradients."""
    from tf_operator_tpu.train.steps import cross_entropy, sharded_lm_xent

    mesh = create_mesh({"dp": 2, "sp": 2, "tp": 2})
    rng = np.random.default_rng(0)
    b, s, d, v = 4, 32, 16, 64
    hidden = jnp.asarray(rng.normal(size=(b, s, d)), jnp.float32)
    kernel = jnp.asarray(rng.normal(size=(d, v)) * 0.3, jnp.float32)
    bias = jnp.asarray(rng.normal(size=(v,)) * 0.1, jnp.float32)
    labels = jnp.asarray(rng.integers(0, v, (b, s)), jnp.int32)

    def naive(hidden, kernel, bias):
        return cross_entropy(hidden @ kernel + bias, labels)

    def sharded(hidden, kernel, bias):
        return sharded_lm_xent(
            mesh, hidden, kernel, bias, labels, chunk=8
        )

    ln, gn = jax.jit(jax.value_and_grad(naive, argnums=(0, 1, 2)))(
        hidden, kernel, bias
    )
    ls, gs = jax.jit(jax.value_and_grad(sharded, argnums=(0, 1, 2)))(
        hidden, kernel, bias
    )
    np.testing.assert_allclose(ln, ls, rtol=1e-6)
    for a, c in zip(gn, gs):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=1e-4, atol=1e-6)

    # No bias; and axes absent from the mesh are treated as unsharded.
    def no_bias(hidden, kernel):
        return sharded_lm_xent(mesh, hidden, kernel, None, labels, chunk=8)

    np.testing.assert_allclose(
        float(jax.jit(no_bias)(hidden, kernel)),
        float(cross_entropy(hidden @ kernel, labels)), rtol=1e-6,
    )
    dp_only = create_mesh({"dp": 4}, jax.devices()[:4])
    np.testing.assert_allclose(
        float(sharded_lm_xent(dp_only, hidden, kernel, bias, labels, chunk=8)),
        float(ln), rtol=1e-6,
    )

    # Tuple data_axis (ZeRO batch over dp x fsdp) with a tp-sharded vocab:
    # the multi-axis token psum + vocab-parallel reduction must still be
    # exact, gradients included.
    zmesh = create_mesh({"dp": 2, "fsdp": 2, "tp": 2})

    def zsharded(hidden, kernel, bias):
        return sharded_lm_xent(
            zmesh, hidden, kernel, bias, labels, chunk=8,
            data_axis=("dp", "fsdp"),
        )

    lz, gz = jax.jit(jax.value_and_grad(zsharded, argnums=(0, 1, 2)))(
        hidden, kernel, bias
    )
    np.testing.assert_allclose(ln, lz, rtol=1e-6)
    for a, c in zip(gn, gz):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=1e-4, atol=1e-6)


def test_lm_step_sharded_xent_matches_naive_step():
    """Full LM train step on dp x sp x tp (ring attention + tp-sharded
    lm_head): the sharded chunked loss reproduces the naive step's loss and
    updated params."""
    mesh = create_mesh({"dp": 2, "sp": 2, "tp": 2})
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq_len=32, dtype=jnp.float32, mesh=mesh,
    )
    model = Transformer(cfg)
    tokens = jnp.zeros((4, 32), jnp.int32)
    params0 = model.init(jax.random.PRNGKey(0), tokens)["params"]
    rules = param_sharding_rules()
    rng = np.random.default_rng(1)
    batch = {
        "tokens": jnp.asarray(rng.integers(0, 64, (4, 32)), jnp.int32),
        "targets": jnp.asarray(rng.integers(0, 64, (4, 32)), jnp.int32),
    }
    tx = adamw(1e-3)
    outs = []
    for chunk in (None, 8):
        params = shard_params_by_rules(mesh, params0, rules)
        state = TrainState.create(params, tx)
        step = make_lm_train_step(
            model, tx, mesh, donate=False, xent_chunk=chunk
        )
        state, metrics = step(state, batch)
        outs.append((float(metrics["loss"]), state.params))
    assert abs(outs[0][0] - outs[1][0]) < 1e-5, (outs[0][0], outs[1][0])
    for a, b in zip(jax.tree.leaves(outs[0][1]), jax.tree.leaves(outs[1][1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-6)


def test_lm_step_fsdp_sharded_state():
    """ZeRO-style LM training: params + adamw moments sharded over fsdp,
    batch over (dp, fsdp), chunked loss — the transformer-side analog of
    the classifier fsdp path. Placement must survive the update and the
    loss must decrease."""
    from tf_operator_tpu.parallel.sharding import (
        fsdp_sharding_tree,
        shard_batch,
        shard_params_fsdp,
    )

    mesh = create_mesh({"dp": 2, "fsdp": 4})
    cfg = TransformerConfig(
        vocab_size=64, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_seq_len=32, dtype=jnp.float32, mesh=None,
    )
    model = Transformer(cfg)
    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(0, 64, (8, 32)), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), toks)["params"]
    tree = fsdp_sharding_tree(mesh, params, min_size=64)
    params = shard_params_fsdp(mesh, params, min_size=64)
    tx = adamw(3e-3)
    state = TrainState.create(params, tx)
    step = make_lm_train_step(
        model, tx, mesh, data_axis=("dp", "fsdp"), seq_axis=None,
        donate=False, param_shardings=tree, xent_chunk=16,
    )
    batch = shard_batch(
        mesh,
        {"tokens": toks, "targets": jnp.roll(toks, -1, axis=1)},
        axis=("dp", "fsdp"),
    )
    losses = []
    for _ in range(20):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])
    # The embedding table is large enough to shard: its placement (and its
    # adamw moment's) must still be the fsdp sharding after updates.
    emb_sharding = state.params["embed"]["embedding"].sharding
    assert "fsdp" in str(emb_sharding.spec), emb_sharding
    mu = state.opt_state[0].mu["embed"]["embedding"].sharding
    assert "fsdp" in str(mu.spec), mu


def test_lm_step_chunked_xent_respects_seq_axis_opt_out():
    """seq_axis=None on a mesh that HAS an sp axis must not shard the loss
    over sp: chunk may equal the full sequence and the loss matches the
    naive step (regression for the sharded-loss routing)."""
    mesh = create_mesh({"dp": 2, "sp": 2, "tp": 2})
    cfg = TransformerConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq_len=32, dtype=jnp.float32, mesh=None,  # no ring attention
    )
    model = Transformer(cfg)
    tokens = jnp.zeros((4, 32), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    rng = np.random.default_rng(2)
    batch = {
        "tokens": jnp.asarray(rng.integers(0, 64, (4, 32)), jnp.int32),
        "targets": jnp.asarray(rng.integers(0, 64, (4, 32)), jnp.int32),
    }
    tx = adamw(1e-3)
    losses = []
    for chunk in (None, 32):  # chunk == FULL seq: only legal when un-sp-sharded
        state = TrainState.create(params, tx)
        step = make_lm_train_step(
            model, tx, mesh, seq_axis=None, donate=False, xent_chunk=chunk
        )
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert abs(losses[0] - losses[1]) < 1e-5, losses


class TestDecode:
    def _cfg(self, **kw):
        base = dict(
            vocab_size=32, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq_len=32, dtype=jnp.float32,
        )
        base.update(kw)
        return TransformerConfig(**base)

    def test_decode_matches_full_forward(self):
        """Step-by-step KV-cache decoding reproduces the training forward's
        logits at every position (teacher forcing)."""
        from dataclasses import replace

        cfg = self._cfg()
        model = Transformer(cfg)
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(rng.integers(0, 32, (2, 12)), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        full = model.apply({"params": params}, tokens)  # [B, T, V]

        dmodel = Transformer(replace(cfg, decode=True))
        cache = dmodel.init(jax.random.PRNGKey(0), tokens[:, :1])["cache"]
        step = jax.jit(
            lambda cache, tok: dmodel.apply(
                {"params": params, "cache": cache}, tok, mutable=["cache"]
            )
        )
        for t in range(tokens.shape[1]):
            logits, updates = step(cache, tokens[:, t : t + 1])
            cache = updates["cache"]
            np.testing.assert_allclose(
                np.asarray(logits[:, 0]), np.asarray(full[:, t]),
                rtol=1e-4, atol=1e-4,
            )

    def test_gqa_decode_matches_full_forward_and_shrinks_cache(self):
        """Grouped-query attention: the training forward repeats KV heads
        while the decode path keeps a grouped [B,S,KV,Dh] cache — the two
        implementations must agree at every position (the strong oracle
        that validates both), and the cache must physically shrink by the
        group factor. Composes with kv_int8."""
        from dataclasses import replace

        cfg = self._cfg(n_kv_heads=2)  # 4 query heads, groups of 2
        model = Transformer(cfg)
        rng = np.random.default_rng(5)
        tokens = jnp.asarray(rng.integers(0, 32, (2, 12)), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        # GQA param tree: split q/kv projections, kv with 2 heads.
        att0 = params["block_0"]["attn"]
        assert set(att0) >= {"q", "kv", "out"} and "qkv" not in att0
        assert att0["kv"]["kernel"].shape == (32, 2, 2, 8)
        full = model.apply({"params": params}, tokens)

        dmodel = Transformer(replace(cfg, decode=True))
        cache = dmodel.init(jax.random.PRNGKey(0), tokens[:, :1])["cache"]
        ck = cache["block_0"]["attn"]["cached_key"]
        assert ck.shape == (2, cfg.max_seq_len, 2, 8)  # KV=2, not H=4
        step = jax.jit(
            lambda cache, tok: dmodel.apply(
                {"params": params, "cache": cache}, tok, mutable=["cache"]
            )
        )
        for t in range(tokens.shape[1]):
            logits, updates = step(cache, tokens[:, t : t + 1])
            cache = updates["cache"]
            np.testing.assert_allclose(
                np.asarray(logits[:, 0]), np.asarray(full[:, t]),
                rtol=1e-4, atol=1e-4,
            )

        # kv_int8 composes with the grouped cache (scales [B,S,KV]).
        from tf_operator_tpu.models.transformer import generate

        kv8 = replace(cfg, decode=True, kv_int8=True)
        cache8 = Transformer(kv8).init(
            jax.random.PRNGKey(0), tokens[:, :1])["cache"]
        att8 = cache8["block_0"]["attn"]
        assert att8["cached_key"].dtype == jnp.int8
        assert att8["key_scale"].shape == (2, cfg.max_seq_len, 2)
        g16 = generate(replace(cfg, kv_int8=False), params,
                       tokens[:, :6], num_steps=6)
        g8 = generate(replace(cfg, kv_int8=True), params,
                      tokens[:, :6], num_steps=6)
        agree = float(np.mean(np.asarray(g16) == np.asarray(g8)))
        assert agree >= 0.75, agree

    def test_batched_prefill_matches_full_forward(self):
        """A multi-token prefill call (the whole prompt in ONE decode-mode
        forward, block-causal attention over the cache) produces the same
        logits as the training forward, and leaves the cache positioned so
        subsequent single-token steps match teacher forcing."""
        from dataclasses import replace

        cfg = self._cfg()
        model = Transformer(cfg)
        rng = np.random.default_rng(1)
        tokens = jnp.asarray(rng.integers(0, 32, (2, 12)), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        full = model.apply({"params": params}, tokens)

        dmodel = Transformer(replace(cfg, decode=True))
        cache = dmodel.init(jax.random.PRNGKey(0), tokens[:, :1])["cache"]
        prefill, updates = dmodel.apply(
            {"params": params, "cache": cache}, tokens[:, :8],
            mutable=["cache"],
        )
        np.testing.assert_allclose(
            np.asarray(prefill), np.asarray(full[:, :8]), rtol=1e-4, atol=1e-4
        )
        cache = updates["cache"]
        for t in range(8, tokens.shape[1]):
            logits, updates = dmodel.apply(
                {"params": params, "cache": cache}, tokens[:, t : t + 1],
                mutable=["cache"],
            )
            cache = updates["cache"]
            np.testing.assert_allclose(
                np.asarray(logits[:, 0]), np.asarray(full[:, t]),
                rtol=1e-4, atol=1e-4,
            )

    def test_generate_learns_plus_one(self):
        """Greedy generation from a model trained on the +1-mod-vocab task
        continues the chain."""
        from tf_operator_tpu.models.transformer import generate

        cfg = self._cfg()
        mesh = create_mesh({"dp": 1}, jax.devices()[:1])
        model = Transformer(cfg)
        rng = np.random.default_rng(0)
        start = rng.integers(0, 32, (8, 1))
        toks = jnp.asarray((start + np.arange(16)) % 32, jnp.int32)
        batch = {"tokens": toks, "targets": jnp.roll(toks, -1, axis=1)}
        params = model.init(jax.random.PRNGKey(0), toks)["params"]
        tx = adamw(5e-3)
        state = TrainState.create(params, tx)
        step = make_lm_train_step(model, tx, mesh, seq_axis=None, donate=False)
        for _ in range(200):
            state, metrics = step(state, batch)
        assert float(metrics["loss"]) < 0.1, float(metrics["loss"])

        # Prompts from the training distribution: the first 4 tokens of two
        # training rows; greedy decode must continue each +1 chain.
        prompt = toks[:2, :4]
        out = generate(cfg, state.params, prompt, num_steps=6)
        expect = np.asarray(toks[:2, 4:10])
        np.testing.assert_array_equal(np.asarray(out), expect)

    def test_tensor_parallel_decode_matches_single_device(self):
        """Decode with tp-sharded params (training shardings) over a
        dp x tp mesh produces the same logits as single-device decode —
        the KV cache shards over heads by GSPMD propagation."""
        from dataclasses import replace

        from tf_operator_tpu.models.transformer import (
            generate,
            param_sharding_rules,
        )
        from tf_operator_tpu.parallel.sharding import shard_params_by_rules

        mesh = create_mesh({"dp": 2, "tp": 2}, devices=jax.devices()[:4])
        cfg = self._cfg()
        cfg_mesh = replace(cfg, mesh=mesh)
        model = Transformer(cfg)
        rng = np.random.default_rng(2)
        tokens = jnp.asarray(rng.integers(0, 32, (2, 10)), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        sharded = shard_params_by_rules(mesh, params, param_sharding_rules())

        def decode_logits(c, p):
            dmodel = Transformer(replace(c, decode=True))
            cache = dmodel.init(jax.random.PRNGKey(0), tokens[:, :1])["cache"]
            logits, updates = dmodel.apply(
                {"params": p, "cache": cache}, tokens[:, :6],
                mutable=["cache"],
            )
            outs = [logits]
            cache = updates["cache"]
            for t in range(6, tokens.shape[1]):
                step_logits, updates = dmodel.apply(
                    {"params": p, "cache": cache}, tokens[:, t : t + 1],
                    mutable=["cache"],
                )
                cache = updates["cache"]
                outs.append(step_logits)
            return jnp.concatenate(outs, axis=1)

        single = decode_logits(cfg, params)
        tp = decode_logits(cfg_mesh, sharded)
        np.testing.assert_allclose(
            np.asarray(tp), np.asarray(single), rtol=1e-4, atol=1e-4
        )
        # And the jitted generate() loop runs end-to-end on the mesh.
        out = generate(cfg_mesh, sharded, tokens[:, :4], num_steps=5)
        assert out.shape == (2, 5)
        assert int(out.min()) >= 0 and int(out.max()) < 32

    def test_generate_budget_and_sampling(self):
        from tf_operator_tpu.models.transformer import generate

        cfg = self._cfg()
        model = Transformer(cfg)
        prompt = jnp.zeros((1, 30), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), prompt)["params"]
        with np.testing.assert_raises(ValueError):
            generate(cfg, params, prompt, num_steps=10)  # 40 > max_seq_len
        out = generate(
            cfg, params, prompt[:, :4], num_steps=5,
            temperature=1.0, rng=jax.random.PRNGKey(1),
        )
        assert out.shape == (1, 5)
        assert int(out.min()) >= 0 and int(out.max()) < 32


def test_fuse_steps_matches_sequential():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tf_operator_tpu.models.mnist import MnistCNN
    from tf_operator_tpu.parallel.mesh import create_mesh
    from tf_operator_tpu.parallel.sharding import replicate, shard_batch
    from tf_operator_tpu.train.steps import (
        TrainState,
        fuse_steps,
        make_classifier_train_step,
        sgd_momentum,
    )

    mesh = create_mesh({"dp": 8})
    model = MnistCNN()
    x = jnp.zeros((8, 28, 28, 1), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=True)
    tx = sgd_momentum(0.05)
    rng = np.random.default_rng(0)
    batch = shard_batch(mesh, {
        "image": rng.normal(size=(16, 28, 28, 1)).astype(np.float32),
        "label": rng.integers(0, 10, size=(16,)).astype(np.int32),
    })

    step = make_classifier_train_step(model, tx, mesh, has_batch_stats=False,
                                      donate=False)
    s_seq = replicate(mesh, TrainState.create(variables["params"], tx))
    for _ in range(3):
        s_seq, m_seq = step(s_seq, batch)

    s_fused = replicate(mesh, TrainState.create(variables["params"], tx))
    s_fused, m_fused = fuse_steps(step, 3, donate=False)(s_fused, batch)

    assert int(s_fused.step) == int(s_seq.step) == 3
    np.testing.assert_allclose(
        float(m_fused["loss"]), float(m_seq["loss"]), rtol=1e-5
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5),
        s_fused.params, s_seq.params,
    )


def test_fuse_steps_scan_batches_consumes_each_slice():
    import jax
    import jax.numpy as jnp
    import numpy as np
    import pytest

    from tf_operator_tpu.models.mnist import MnistCNN
    from tf_operator_tpu.parallel.mesh import create_mesh
    from tf_operator_tpu.parallel.sharding import replicate
    from tf_operator_tpu.train.steps import (
        TrainState,
        fuse_steps,
        make_classifier_train_step,
        sgd_momentum,
    )

    mesh = create_mesh({"dp": 8})
    model = MnistCNN()
    x = jnp.zeros((8, 28, 28, 1), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=True)
    tx = sgd_momentum(0.05)
    rng = np.random.default_rng(1)
    batches = [
        {
            "image": rng.normal(size=(16, 28, 28, 1)).astype(np.float32),
            "label": rng.integers(0, 10, size=(16,)).astype(np.int32),
        }
        for _ in range(3)
    ]
    stacked = jax.tree.map(lambda *xs: np.stack(xs), *batches)

    step = make_classifier_train_step(model, tx, mesh, has_batch_stats=False,
                                      donate=False)
    s_seq = replicate(mesh, TrainState.create(variables["params"], tx))
    for b in batches:
        s_seq, m_seq = step(s_seq, jax.tree.map(jnp.asarray, b))

    s_f = replicate(mesh, TrainState.create(variables["params"], tx))
    fused = fuse_steps(step, 3, scan_batches=True, donate=False)
    s_f, m_f = fused(s_f, jax.tree.map(jnp.asarray, stacked))
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5),
        s_f.params, s_seq.params,
    )

    with pytest.raises(ValueError, match="leading dim"):
        fused(s_f, jax.tree.map(jnp.asarray, batches[0]))


class TestInt8Decode:
    """Weight-only int8 decode (ops/int8_dense.py + int8_decode=True):
    the HBM-traffic optimization for the decode roofline. CPU runs the
    XLA dispatch leg; the Pallas kernel itself is pinned against the same
    formula in tests/test_ops.py::TestInt8Dense."""

    def _cfg(self, **kw):
        base = dict(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq_len=32, dtype=jnp.bfloat16,
        )
        base.update(kw)
        return TransformerConfig(**base)

    def _trained_params(self, cfg, seed=0):
        model = Transformer(cfg)
        tokens = jnp.zeros((2, 8), jnp.int32)
        return model.init(jax.random.PRNGKey(seed), tokens)["params"]

    def test_quantized_tree_halves_projection_bytes(self):
        from dataclasses import replace

        cfg = self._cfg()
        params = self._trained_params(cfg)
        params_bf16 = jax.tree.map(
            lambda x: x.astype(jnp.bfloat16), params
        )
        qparams = quantize_decode_params(params_bf16)
        # Quantized tree must load into the int8 decode model.
        dmodel = Transformer(replace(cfg, decode=True, int8_decode=True))
        cache = dmodel.init(
            jax.random.PRNGKey(0), jnp.zeros((2, 1), jnp.int32)
        )["cache"]
        logits, _ = dmodel.apply(
            {"params": qparams, "cache": cache},
            jnp.zeros((2, 1), jnp.int32), mutable=["cache"],
        )
        assert logits.shape == (2, 1, cfg.vocab_size)

        def nbytes(tree):
            return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(tree))

        def proj_bytes(tree, quantized):
            total = 0
            for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
                keys = [getattr(p, "key", "") for p in path]
                name = "kernel_q" if quantized else "kernel"
                if name in keys and any(
                    t in keys for t in
                    ("qkv", "out", "in_proj", "out_proj", "lm_head")
                ):
                    total += leaf.size * leaf.dtype.itemsize
            return total

        # Projection kernels: int8 is exactly half of bf16.
        assert proj_bytes(qparams, True) * 2 == proj_bytes(params_bf16, False)
        assert nbytes(qparams) < nbytes(params_bf16)

    def test_int8_logits_close_and_generate_runs(self):
        """Prefill logits through the int8 path track the bf16 decode
        model within weight-only-int8 tolerance, and the jitted generate
        loop runs end-to-end with the quantized tree."""
        from dataclasses import replace

        from tf_operator_tpu.models.transformer import generate

        cfg = self._cfg()
        params = self._trained_params(cfg, seed=3)
        prompt = jnp.asarray(
            np.random.default_rng(0).integers(0, 64, (2, 6)), jnp.int32
        )

        ref_model = Transformer(replace(cfg, decode=True))
        cache = ref_model.init(jax.random.PRNGKey(0), prompt[:, :1])["cache"]
        ref_logits, _ = ref_model.apply(
            {"params": params, "cache": cache}, prompt, mutable=["cache"]
        )

        qparams = quantize_decode_params(params)
        q_model = Transformer(replace(cfg, decode=True, int8_decode=True))
        qcache = q_model.init(jax.random.PRNGKey(0), prompt[:, :1])["cache"]
        q_logits, _ = q_model.apply(
            {"params": qparams, "cache": qcache}, prompt, mutable=["cache"]
        )
        ref_np, q_np = np.asarray(ref_logits), np.asarray(q_logits)
        scale = np.abs(ref_np).max()
        assert np.abs(q_np - ref_np).max() < 0.1 * scale, (
            np.abs(q_np - ref_np).max(), scale
        )

        toks = generate(
            replace(cfg, int8_decode=True), qparams, prompt, num_steps=5
        )
        assert toks.shape == (2, 5)
        assert int(toks.min()) >= 0 and int(toks.max()) < cfg.vocab_size
        # Deterministic greedy: same call -> same tokens.
        toks2 = generate(
            replace(cfg, int8_decode=True), qparams, prompt, num_steps=5
        )
        np.testing.assert_array_equal(np.asarray(toks), np.asarray(toks2))

    def test_gqa_split_projections_quantize(self):
        """quantize_decode_params handles the GQA param tree (split q/kv
        projections) and int8+GQA generation runs end-to-end."""
        from dataclasses import replace

        from tf_operator_tpu.models.transformer import generate

        cfg = self._cfg(n_kv_heads=2)
        prompt = jnp.asarray(
            np.random.default_rng(2).integers(0, 64, (2, 4)), jnp.int32
        )
        params = Transformer(cfg).init(
            jax.random.PRNGKey(1), prompt[:, :1]
        )["params"]
        qparams = quantize_decode_params(
            jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)
        )
        att = qparams["block_0"]["attn"]
        assert "kernel_q" in att["q"] and "kernel_q" in att["kv"]
        assert att["kv"]["kernel_q"].shape == (32, 2 * 2 * 8)
        toks = generate(
            replace(cfg, int8_decode=True, kv_int8=True), qparams,
            prompt, num_steps=4,
        )
        assert toks.shape == (2, 4)

    def test_moe_params_pass_through_unquantized(self):
        cfg = self._cfg(moe_every_n=2)
        params = self._trained_params(cfg)
        qparams = quantize_decode_params(params)
        moe = qparams["block_1"]["moe"]
        assert set(moe) == set(params["block_1"]["moe"])
        assert "kernel_q" not in str(jax.tree_util.tree_structure(moe))
        # Dense blocks still quantized.
        assert "kernel_q" in qparams["block_0"]["mlp"]["in_proj"]


class TestKvInt8Decode:
    """int8 KV cache (kv_int8=True): the cache-read half of the decode
    roofline. The scale factors out of both attention dots, so the int8
    buffers feed the matmuls directly — pinned here: cache layout/bytes,
    logit closeness to the bf16-cache path, greedy-token agreement, and
    composition with weight-only int8."""

    def _cfg(self, **kw):
        base = dict(
            vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq_len=32, dtype=jnp.bfloat16, decode=True,
        )
        base.update(kw)
        return TransformerConfig(**base)

    def test_cache_is_int8_and_half_the_bytes(self):
        from dataclasses import replace

        cfg = self._cfg()
        params = Transformer(cfg).init(
            jax.random.PRNGKey(0), jnp.zeros((2, 1), jnp.int32)
        )["params"]
        kv8 = Transformer(replace(cfg, kv_int8=True))
        cache8 = kv8.init(
            jax.random.PRNGKey(0), jnp.zeros((2, 1), jnp.int32)
        )["cache"]
        cache16 = Transformer(cfg).init(
            jax.random.PRNGKey(0), jnp.zeros((2, 1), jnp.int32)
        )["cache"]

        def kv_bytes(cache):
            return sum(
                leaf.size * leaf.dtype.itemsize
                for path, leaf in
                jax.tree_util.tree_flatten_with_path(cache)[0]
                if any("cached_" in str(getattr(p, "key", "")) for p in path)
            )

        # int8 K/V buffers are exactly half the bf16 ones; the scale
        # sidecar is 1/head_dim of that — total well under 60%.
        assert kv_bytes(cache8) * 2 == kv_bytes(cache16)
        blocks = [v for k, v in cache8.items() if k.startswith("block_")]
        assert blocks
        for layer in blocks:
            att = layer["attn"]
            assert att["cached_key"].dtype == jnp.int8
            assert att["cached_value"].dtype == jnp.int8
            assert att["key_scale"].dtype == jnp.float32
        # And it runs: one prefill step through the quantized cache.
        logits, _ = kv8.apply(
            {"params": params, "cache": cache8},
            jnp.zeros((2, 4), jnp.int32), mutable=["cache"],
        )
        assert logits.shape == (2, 4, cfg.vocab_size)

    def test_kv8_logits_close_and_greedy_agrees(self):
        """Prefill logits with the int8 cache track the bf16-cache decode
        within per-(token,head) symmetric-quant tolerance, and greedy
        generation agrees token-for-token on a real (trained-ish) model."""
        from dataclasses import replace

        from tf_operator_tpu.models.transformer import generate

        cfg = self._cfg()
        rng = np.random.default_rng(7)
        prompt = jnp.asarray(rng.integers(0, 64, (2, 6)), jnp.int32)
        params = Transformer(cfg).init(
            jax.random.PRNGKey(5), prompt[:, :1]
        )["params"]

        ref_model = Transformer(cfg)
        cache = ref_model.init(jax.random.PRNGKey(0), prompt[:, :1])["cache"]
        ref_logits, _ = ref_model.apply(
            {"params": params, "cache": cache}, prompt, mutable=["cache"]
        )
        kv8_model = Transformer(replace(cfg, kv_int8=True))
        cache8 = kv8_model.init(jax.random.PRNGKey(0), prompt[:, :1])["cache"]
        kv8_logits, _ = kv8_model.apply(
            {"params": params, "cache": cache8}, prompt, mutable=["cache"]
        )
        ref_np, q_np = np.asarray(ref_logits), np.asarray(kv8_logits)
        scale = np.abs(ref_np).max()
        assert np.abs(q_np - ref_np).max() < 0.05 * scale, (
            np.abs(q_np - ref_np).max(), scale
        )

        g16 = generate(cfg, params, prompt, num_steps=8)
        g8 = generate(replace(cfg, kv_int8=True), params, prompt, num_steps=8)
        agree = float(np.mean(np.asarray(g16) == np.asarray(g8)))
        assert agree >= 0.75, f"greedy agreement {agree}"
        # Deterministic: same call -> same tokens.
        g8b = generate(
            replace(cfg, kv_int8=True), params, prompt, num_steps=8
        )
        np.testing.assert_array_equal(np.asarray(g8), np.asarray(g8b))

    def test_kv8_under_tensor_parallel_decode(self):
        """kv_int8 is pure XLA (no custom-call), so GSPMD partitions it
        under tp-sharded params like the bf16 cache — serve_lm documents
        '--kv-int8 works under --tp' and this pins it: token-identical
        to the unsharded kv8 decode."""
        from dataclasses import replace

        from tf_operator_tpu.models.transformer import (
            generate,
            param_sharding_rules,
        )
        from tf_operator_tpu.parallel.sharding import shard_params_by_rules

        cfg = self._cfg()
        prompt = jnp.asarray(
            np.random.default_rng(0).integers(0, 64, (2, 6)), jnp.int32
        )
        params = Transformer(cfg).init(
            jax.random.PRNGKey(5), prompt[:, :1]
        )["params"]
        kv8 = replace(cfg, kv_int8=True)
        g_plain = generate(kv8, params, prompt, num_steps=6)
        mesh = create_mesh({"tp": 2}, jax.devices()[:2])
        params_tp = shard_params_by_rules(
            mesh, params, param_sharding_rules()
        )
        g_tp = generate(kv8, params_tp, prompt, num_steps=6)
        # tp changes matmul reduction order, so a near-tied argmax may
        # flip at float epsilon — agreement threshold, not exactness
        # (same reasoning as test_tensor_parallel_decode_matches_single_
        # device's allclose).
        agree = float(np.mean(np.asarray(g_plain) == np.asarray(g_tp)))
        assert agree >= 0.75, (agree, g_plain, g_tp)

    def test_composes_with_weight_int8(self):
        from dataclasses import replace

        from tf_operator_tpu.models.transformer import generate

        cfg = self._cfg()
        prompt = jnp.asarray(
            np.random.default_rng(1).integers(0, 64, (2, 4)), jnp.int32
        )
        params = Transformer(cfg).init(
            jax.random.PRNGKey(2), prompt[:, :1]
        )["params"]
        qparams = quantize_decode_params(
            jax.tree.map(lambda x: x.astype(jnp.bfloat16), params)
        )
        both = replace(cfg, int8_decode=True, kv_int8=True)
        toks = generate(both, qparams, prompt, num_steps=5)
        assert toks.shape == (2, 5)
        assert int(toks.min()) >= 0 and int(toks.max()) < cfg.vocab_size


class TestLargeBatchOptimizers:
    """LARS / LAMB — the layerwise-adaptive optimizers of the MLPerf
    TPU-pod large-batch recipes (retrieved-papers list). Training still
    converges, and LAMB's moments shard under weight-update sharding
    exactly like adamw's (the composition the docstrings promise)."""

    def test_lars_trains_classifier(self):
        from tf_operator_tpu.models.mnist import MnistCNN
        from tf_operator_tpu.train.steps import (
            TrainState,
            lars,
            make_classifier_train_step,
            warmup_cosine,
        )

        mesh = create_mesh({"dp": 1}, jax.devices()[:1])
        model = MnistCNN(dtype=jnp.float32)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(32, 28, 28, 1)), jnp.float32)
        y = jnp.asarray(rng.integers(0, 10, (32,)), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), x, train=True)["params"]
        tx = lars(warmup_cosine(0.5, 80, warmup_steps=5))
        state = TrainState.create(params, tx)
        step = make_classifier_train_step(
            model, tx, mesh, has_batch_stats=False, donate=False
        )
        batch = {"image": x, "label": y}
        first = None
        for _ in range(80):
            state, m = step(state, batch)
            if first is None:
                first = float(m["loss"])
        assert float(m["loss"]) < first * 0.5, (first, float(m["loss"]))

    def test_lamb_trains_lm_and_shards_moments(self):
        from tf_operator_tpu.parallel.sharding import (
            replicate,
            shard_batch,
            weight_update_shardings,
        )
        from tf_operator_tpu.train.steps import (
            TrainState,
            lamb,
            make_lm_train_step,
        )

        mesh = create_mesh({"dp": 8})
        cfg = TransformerConfig(
            vocab_size=32, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq_len=16, dtype=jnp.float32,
        )
        model = Transformer(cfg)
        rng = np.random.default_rng(1)
        start = rng.integers(0, 32, (16, 1))
        toks = jnp.asarray((start + np.arange(16)) % 32, jnp.int32)
        params = replicate(mesh, model.init(
            jax.random.PRNGKey(0), toks)["params"])
        tx = lamb(5e-3)
        state = TrainState.create(params, tx)
        opt_sh = weight_update_shardings(mesh, state.opt_state, min_size=64)
        state = state.replace(opt_state=jax.tree.map(
            jax.device_put, state.opt_state, opt_sh))
        step = make_lm_train_step(
            model, tx, mesh, seq_axis=None, donate=False,
            opt_shardings=opt_sh,
        )
        batch = shard_batch(
            mesh, {"tokens": toks, "targets": jnp.roll(toks, -1, 1)}
        )
        first = None
        for _ in range(40):
            state, m = step(state, batch)
            if first is None:
                first = float(m["loss"])
        assert float(m["loss"]) < first * 0.6, (first, float(m["loss"]))
        assert any(
            "dp" in str(getattr(leaf.sharding, "spec", ""))
            for leaf in jax.tree.leaves(state.opt_state)
            if hasattr(leaf, "sharding") and leaf.size >= 64
        ), "LAMB moments not sharded under weight-update sharding"


class TestAdafactor:
    def test_adafactor_state_is_factored_and_trains(self):
        """Adafactor's second-moment state for a [d_in, d_out] kernel is
        O(d_in + d_out), not O(d_in * d_out) — the reason it exists — and
        the LM still learns under it."""
        from tf_operator_tpu.train.steps import adafactor

        mesh = create_mesh({"dp": 1}, jax.devices()[:1])
        # Dims >= 128: optax.adafactor only factors axes at least
        # min_dim_size_to_factor (128) long — real LM shapes qualify.
        cfg = TransformerConfig(
            vocab_size=256, d_model=128, n_heads=4, n_layers=2, d_ff=256,
            max_seq_len=32, dtype=jnp.float32,
        )
        model = Transformer(cfg)
        rng = np.random.default_rng(11)
        start = rng.integers(0, 256, (8, 1))
        chain = (start + np.arange(17)) % 256
        batch = {
            "tokens": jnp.asarray(chain[:, :-1], jnp.int32),
            "targets": jnp.asarray(chain[:, 1:], jnp.int32),
        }
        params = model.init(jax.random.PRNGKey(0), batch["tokens"])["params"]
        tx = adafactor(2e-2)
        n_params = sum(x.size for x in jax.tree.leaves(params))
        opt_state = tx.init(params)
        n_opt = sum(
            x.size for x in jax.tree.leaves(opt_state)
            if hasattr(x, "size")
        )
        # AdamW would carry 2x n_params; factored moments are far smaller.
        assert n_opt < n_params, (n_opt, n_params)

        state = TrainState.create(params, tx)
        step = make_lm_train_step(model, tx, mesh, seq_axis=None,
                                  donate=False)
        losses = []
        for _ in range(120):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


class TestNucleusSampling:
    def test_tiny_top_p_equals_greedy(self):
        """top_p small enough that the nucleus is exactly the argmax token
        must reproduce greedy decoding deterministically."""
        from tf_operator_tpu.models.transformer import generate

        cfg = TransformerConfig(
            vocab_size=32, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq_len=32, dtype=jnp.float32,
        )
        model = Transformer(cfg)
        prompt = jnp.asarray(
            np.random.default_rng(12).integers(0, 32, (2, 6)), jnp.int32
        )
        params = model.init(jax.random.PRNGKey(0), prompt)["params"]
        greedy = generate(cfg, params, prompt, num_steps=6)
        nucleus = generate(
            cfg, params, prompt, num_steps=6, temperature=1.0,
            top_p=1e-9, rng=jax.random.PRNGKey(3),
        )
        np.testing.assert_array_equal(np.asarray(nucleus), np.asarray(greedy))

    def test_top_p_one_samples_full_distribution(self):
        from tf_operator_tpu.models.transformer import generate

        cfg = TransformerConfig(
            vocab_size=32, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq_len=32, dtype=jnp.float32,
        )
        model = Transformer(cfg)
        prompt = jnp.zeros((1, 4), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), prompt)["params"]
        out = generate(
            cfg, params, prompt, num_steps=8, temperature=1.0, top_p=1.0,
            rng=jax.random.PRNGKey(4),
        )
        assert out.shape == (1, 8)
        assert int(out.min()) >= 0 and int(out.max()) < 32

    def test_top_p_validated(self):
        from tf_operator_tpu.models.transformer import generate

        cfg = TransformerConfig(
            vocab_size=32, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_seq_len=32, dtype=jnp.float32,
        )
        model = Transformer(cfg)
        prompt = jnp.zeros((1, 4), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), prompt)["params"]
        with pytest.raises(ValueError, match="top_p"):
            generate(cfg, params, prompt, num_steps=2, temperature=1.0,
                     top_p=1.5, rng=jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="top_p"):
            generate(cfg, params, prompt, num_steps=2, top_p=0.9)

    def test_nucleus_filter_masks_tail(self):
        from tf_operator_tpu.models.transformer import _nucleus_filter

        logits = jnp.log(jnp.asarray([[0.5, 0.3, 0.15, 0.05]]))
        # top_p=0.7: nucleus = {0.5, 0.3} (0.5 < 0.7, crossing token 0.3
        # included); tail masked.
        out = np.asarray(_nucleus_filter(logits, 0.7))
        assert out[0, 0] > -1e29 and out[0, 1] > -1e29
        assert out[0, 2] <= -1e29 and out[0, 3] <= -1e29


class TestGradAccumulation:
    def test_grad_accum_matches_full_batch(self):
        """grad_accum=4 (microbatched gradients inside one jitted step)
        produces the same loss and the same updated params as the full
        batch — exact for the per-token-mean LM loss."""
        mesh = create_mesh({"dp": 1}, jax.devices()[:1])
        cfg = TransformerConfig(
            vocab_size=64, d_model=64, n_heads=4, n_layers=2, d_ff=128,
            max_seq_len=32, dtype=jnp.float32,
        )
        model = Transformer(cfg)
        rng = np.random.default_rng(13)
        tokens = jnp.asarray(rng.integers(0, 64, (8, 16)), jnp.int32)
        batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, axis=1)}
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        # SGD: linear in the gradients, so the only accum-vs-full delta is
        # f32 reassociation (~1e-7). Adam would amplify that noise through
        # g/sqrt(v) normalization into lr-scale update flips on near-zero
        # gradient entries.
        tx = sgd_momentum(0.1)

        results = []
        for accum in (1, 4):
            state = TrainState.create(params, tx)
            step = make_lm_train_step(
                model, tx, mesh, seq_axis=None, donate=False,
                grad_accum=accum,
            )
            state, metrics = step(state, batch)
            results.append((float(metrics["loss"]), state.params))
        assert abs(results[0][0] - results[1][0]) < 1e-6, (
            results[0][0], results[1][0],
        )
        diffs = jax.tree.map(
            lambda a, b: float(jnp.abs(a - b).max()),
            results[0][1], results[1][1],
        )
        assert max(jax.tree.leaves(diffs)) < 1e-6, diffs

    def test_grad_accum_validates(self):
        mesh = create_mesh({"dp": 1}, jax.devices()[:1])
        cfg = TransformerConfig(
            vocab_size=16, d_model=16, n_heads=2, n_layers=1, d_ff=32,
            max_seq_len=8, dtype=jnp.float32,
        )
        model = Transformer(cfg)
        tokens = jnp.zeros((6, 8), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        tx = adamw(1e-3)
        with pytest.raises(ValueError, match="grad_accum"):
            make_lm_train_step(model, tx, mesh, grad_accum=0)
        state = TrainState.create(params, tx)
        step = make_lm_train_step(
            model, tx, mesh, seq_axis=None, donate=False, grad_accum=4
        )
        with pytest.raises(ValueError, match="divisible"):
            step(state, {"tokens": tokens, "targets": tokens})  # 6 % 4
