"""Parallel layer tests on the virtual 8-device CPU mesh: mesh construction,
sharding rules, ring attention exactness (fwd + grad)."""

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from tf_operator_tpu.parallel.mesh import create_mesh, host_local_batch_size
from tf_operator_tpu.parallel.ring_attention import (
    reference_attention,
    ring_attention,
)
from tf_operator_tpu.parallel.sharding import (
    batch_sharded,
    shard_batch,
    shard_params_by_rules,
)


class TestMesh:
    def test_create_explicit(self):
        mesh = create_mesh({"dp": 2, "tp": 4})
        assert mesh.shape == {"dp": 2, "tp": 4}

    def test_wildcard(self):
        mesh = create_mesh({"dp": -1, "tp": 2})
        assert mesh.shape["dp"] == 4

    def test_axis_order_canonical(self):
        mesh = create_mesh({"tp": 2, "dp": 2, "sp": 2})
        assert tuple(mesh.axis_names) == ("dp", "sp", "tp")

    def test_bad_sizes(self):
        with pytest.raises(ValueError):
            create_mesh({"dp": 3, "tp": 3})

    def test_local_batch(self):
        mesh = create_mesh({"dp": 4, "tp": 2})
        assert host_local_batch_size(32, mesh) == 8
        with pytest.raises(ValueError):
            host_local_batch_size(30, mesh)


class TestSharding:
    def test_shard_batch(self):
        mesh = create_mesh({"dp": 8})
        batch = {"x": jnp.ones((16, 4))}
        out = shard_batch(mesh, batch)
        assert out["x"].sharding == batch_sharded(mesh)

    def test_param_rules(self):
        mesh = create_mesh({"dp": 2, "tp": 4})
        params = {
            "mlp": {"in_proj": {"kernel": jnp.ones((8, 16))}},
            "norm": {"scale": jnp.ones((8,))},
        }
        out = shard_params_by_rules(
            mesh, params, {"in_proj/kernel": (None, "tp")}
        )
        assert out["mlp"]["in_proj"]["kernel"].sharding.spec == P(None, "tp")
        assert out["norm"]["scale"].sharding.spec == P()


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, causal):
        mesh = create_mesh({"dp": 2, "sp": 4})
        key = jax.random.PRNGKey(0)
        B, T, H, D = 2, 32, 4, 16
        q, k, v = (
            jax.random.normal(jax.random.fold_in(key, i), (B, T, H, D), jnp.float32)
            for i in range(3)
        )
        out = ring_attention(q, k, v, mesh, causal=causal)
        ref = reference_attention(q, k, v, causal=causal)
        assert float(jnp.abs(out - ref).max()) < 1e-5

    def test_gradients_match(self):
        mesh = create_mesh({"dp": 2, "sp": 4})
        key = jax.random.PRNGKey(1)
        B, T, H, D = 2, 16, 2, 8
        q, k, v = (
            jax.random.normal(jax.random.fold_in(key, i), (B, T, H, D), jnp.float32)
            for i in range(3)
        )
        for arg in range(3):
            g_ring = jax.grad(
                lambda *a: ring_attention(*a, mesh, causal=True).sum(), argnums=arg
            )(q, k, v)
            g_ref = jax.grad(
                lambda *a: reference_attention(*a, causal=True).sum(), argnums=arg
            )(q, k, v)
            assert float(jnp.abs(g_ring - g_ref).max()) < 1e-5, f"arg {arg}"

    def test_sp8_full_ring(self):
        mesh = create_mesh({"sp": 8})
        key = jax.random.PRNGKey(2)
        B, T, H, D = 1, 64, 2, 8
        q, k, v = (
            jax.random.normal(jax.random.fold_in(key, i), (B, T, H, D), jnp.float32)
            for i in range(3)
        )
        out = ring_attention(q, k, v, mesh, causal=True, batch_spec=(None,))
        ref = reference_attention(q, k, v, causal=True)
        assert float(jnp.abs(out - ref).max()) < 1e-5
