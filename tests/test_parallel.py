"""Parallel layer tests on the virtual 8-device CPU mesh: mesh construction,
sharding rules, fsdp (sharded params/optimizer state, dp equivalence,
collective insertion), ring attention exactness (fwd + grad)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tf_operator_tpu.parallel.mesh import create_mesh, host_local_batch_size
from tf_operator_tpu.parallel.ring_attention import (
    reference_attention,
    ring_attention,
)
from tf_operator_tpu.parallel.sharding import (
    batch_sharded,
    fsdp_sharding_tree,
    replicate,
    shard_batch,
    shard_params_by_rules,
    shard_params_fsdp,
)


class TestMesh:
    def test_create_explicit(self):
        mesh = create_mesh({"dp": 2, "tp": 4})
        assert mesh.shape == {"dp": 2, "tp": 4}

    def test_wildcard(self):
        mesh = create_mesh({"dp": -1, "tp": 2})
        assert mesh.shape["dp"] == 4

    def test_axis_order_canonical(self):
        mesh = create_mesh({"tp": 2, "dp": 2, "sp": 2})
        assert tuple(mesh.axis_names) == ("dp", "sp", "tp")

    def test_bad_sizes(self):
        with pytest.raises(ValueError):
            create_mesh({"dp": 3, "tp": 3})

    def test_local_batch(self):
        mesh = create_mesh({"dp": 4, "tp": 2})
        assert host_local_batch_size(32, mesh) == 8
        with pytest.raises(ValueError):
            host_local_batch_size(30, mesh)


class TestSharding:
    def test_shard_batch(self):
        mesh = create_mesh({"dp": 8})
        batch = {"x": jnp.ones((16, 4))}
        out = shard_batch(mesh, batch)
        assert out["x"].sharding == batch_sharded(mesh)

    def test_param_rules(self):
        mesh = create_mesh({"dp": 2, "tp": 4})
        params = {
            "mlp": {"in_proj": {"kernel": jnp.ones((8, 16))}},
            "norm": {"scale": jnp.ones((8,))},
        }
        out = shard_params_by_rules(
            mesh, params, {"in_proj/kernel": (None, "tp")}
        )
        assert out["mlp"]["in_proj"]["kernel"].sharding.spec == P(None, "tp")
        assert out["norm"]["scale"].sharding.spec == P()


class TestWeightUpdateSharding:
    """ZeRO-1 weight-update sharding over plain dp (arXiv:2004.13336):
    moments sharded, params replicated, forward/backward untouched.
    Oracle: the identical step with fully-replicated state."""

    def _setup(self, opt_sharded: bool):
        from tf_operator_tpu.models.transformer import (
            Transformer,
            TransformerConfig,
        )
        from tf_operator_tpu.parallel.sharding import (
            weight_update_shardings,
        )
        from tf_operator_tpu.train.steps import (
            TrainState,
            adamw,
            make_lm_train_step,
        )

        cfg = TransformerConfig(
            vocab_size=64, d_model=64, n_heads=4, n_layers=2, d_ff=128,
            max_seq_len=32, dtype=jnp.float32,
        )
        model = Transformer(cfg)
        rng = np.random.default_rng(0)
        toks = jnp.asarray(rng.integers(0, 64, (16, 16)), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), toks)["params"]
        tx = adamw(1e-3)
        mesh = create_mesh({"dp": 8})
        params = replicate(mesh, params)
        state = TrainState.create(params, tx)
        opt_sh = None
        if opt_sharded:
            opt_sh = weight_update_shardings(
                mesh, state.opt_state, min_size=64
            )
            state = state.replace(opt_state=jax.tree.map(
                jax.device_put, state.opt_state, opt_sh))
        # No param_shardings on purpose: the step must default the
        # replicated param pin when opt_shardings is set — without it
        # GSPMD propagates the sharded update into new_params (silent
        # FSDP); the replicated-params assertion below pins the default.
        step = make_lm_train_step(
            model, tx, mesh, seq_axis=None, donate=False,
            opt_shardings=opt_sh,
        )
        batch = shard_batch(
            mesh, {"tokens": toks, "targets": jnp.roll(toks, -1, 1)}
        )
        return state, step, batch, opt_sh

    def test_matches_replicated_and_stays_sharded(self):
        state_r, step_r, batch, _ = self._setup(opt_sharded=False)
        state_w, step_w, _, opt_sh = self._setup(opt_sharded=True)

        for _ in range(3):
            state_r, m_r = step_r(state_r, batch)
            state_w, m_w = step_w(state_w, batch)
        np.testing.assert_allclose(
            float(m_w["loss"]), float(m_r["loss"]), rtol=1e-5)
        # Params after 3 adamw steps: m/(sqrt(v)+eps) amplifies fp32
        # roundoff from the sharded-update reduction layout on near-zero
        # grads — absolute-dominated bound (loss rtol above is the tight
        # semantic check, same convention as the 1f1b-vs-gpipe test).
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=5e-4, rtol=1e-3),
            state_w.params, state_r.params,
        )
        # Moments are PHYSICALLY sharded after steps: a big adam mu leaf
        # holds 1/8 of its rows per device and its spec names dp.
        big = [
            leaf for leaf in jax.tree.leaves(state_w.opt_state)
            if hasattr(leaf, "sharding") and leaf.size >= 64
            and "dp" in str(getattr(leaf.sharding, "spec", ""))
        ]
        assert big, "no sharded optimizer moment survived the step"
        sample = max(big, key=lambda a: a.size)
        full = np.prod(sample.shape)
        assert (
            np.prod(sample.addressable_shards[0].data.shape) * 8 == full
        ), (sample.shape, sample.addressable_shards[0].data.shape)
        # Params stayed replicated (no FSDP gather was introduced).
        for leaf in jax.tree.leaves(state_w.params):
            assert "dp" not in str(getattr(leaf.sharding, "spec", "")), (
                leaf.sharding)


class TestFsdp:
    """Parameter+optimizer-state sharding over the data axis — the TPU
    analog of the reference's PS state distribution (SURVEY.md §2.9)."""

    def _setup(self, donate=False):
        from tf_operator_tpu.models.mnist import MnistCNN
        from tf_operator_tpu.train.steps import (
            TrainState,
            adamw,
            make_classifier_train_step,
        )

        model = MnistCNN(dtype=jnp.float32)
        x = jax.random.normal(jax.random.PRNGKey(0), (16, 28, 28, 1))
        y = jax.random.randint(jax.random.PRNGKey(1), (16,), 0, 10)
        params = model.init(jax.random.PRNGKey(2), x, train=True)["params"]
        tx = adamw(1e-3)
        mesh = create_mesh({"fsdp": 8})
        tree = fsdp_sharding_tree(mesh, params, min_size=64)
        state = TrainState.create(shard_params_fsdp(mesh, params, min_size=64), tx)
        step = make_classifier_train_step(
            model, tx, mesh, has_batch_stats=False, data_axis="fsdp",
            param_shardings=tree, donate=donate,
        )
        batch = shard_batch(mesh, {"image": x, "label": y}, axis="fsdp")
        return model, tx, params, x, y, mesh, tree, state, step, batch

    def test_sharding_tree_rules(self):
        mesh = create_mesh({"fsdp": 8})
        params = {
            "dense": {"kernel": jnp.ones((16, 256)), "bias": jnp.ones((256,))},
            "odd": jnp.ones((129, 3)),   # no dim divisible by 8
            "tiny": jnp.ones((8, 8)),    # under min_size
        }
        tree = fsdp_sharding_tree(mesh, params, min_size=128)
        # largest divisible dim sharded
        assert tree["dense"]["kernel"].spec == P(None, "fsdp")
        assert tree["dense"]["bias"].spec == P("fsdp")
        # indivisible and small arrays stay replicated
        assert tree["odd"].spec == P()
        assert tree["tiny"].spec == P()

    def test_state_physically_sharded(self):
        *_, state, step, batch = self._setup()
        k = state.params["Dense_0"]["kernel"]
        assert k.addressable_shards[0].data.shape[0] == k.shape[0] // 8
        # adamw moments inherit the sharded placement (the PS-state analog)
        mu = state.opt_state[0].mu
        assert mu["Dense_0"]["kernel"].sharding.spec == P("fsdp", None)

    def test_collectives_inserted(self):
        """The fsdp step must gather shards for compute and reduce grads.

        On TPU the gradient collective is a reduce-scatter; the CPU test
        backend lowers it as all-reduce + slice, so accept either.
        """
        *_, state, step, batch = self._setup()
        txt = step.lower(state, batch).compile().as_text()
        assert "all-gather" in txt
        assert "reduce-scatter" in txt or "all-reduce" in txt

    def test_numerical_equivalence_vs_dp(self):
        from tf_operator_tpu.parallel.sharding import replicate
        from tf_operator_tpu.train.steps import (
            TrainState,
            make_classifier_train_step,
        )

        model, tx, params, x, y, *_ = self._setup()
        _, _, _, _, _, _, _, fs_state, fs_step, fs_batch = self._setup()
        dp_mesh = create_mesh({"dp": 8})
        dp_state = replicate(dp_mesh, TrainState.create(params, tx))
        dp_step = make_classifier_train_step(
            model, tx, dp_mesh, has_batch_stats=False, donate=False
        )
        dp_batch = shard_batch(dp_mesh, {"image": x, "label": y})
        for _ in range(3):
            dp_state, dp_metrics = dp_step(dp_state, dp_batch)
            fs_state, fs_metrics = fs_step(fs_state, fs_batch)
        assert float(dp_metrics["loss"]) == pytest.approx(
            float(fs_metrics["loss"]), abs=1e-5
        )
        diffs = jax.tree.map(
            lambda a, b: float(jnp.abs(a - b).max()), dp_state.params, fs_state.params
        )
        assert max(jax.tree.leaves(diffs)) < 1e-4


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, causal):
        mesh = create_mesh({"dp": 2, "sp": 4})
        key = jax.random.PRNGKey(0)
        B, T, H, D = 2, 32, 4, 16
        q, k, v = (
            jax.random.normal(jax.random.fold_in(key, i), (B, T, H, D), jnp.float32)
            for i in range(3)
        )
        out = ring_attention(q, k, v, mesh, causal=causal)
        ref = reference_attention(q, k, v, causal=causal)
        assert float(jnp.abs(out - ref).max()) < 1e-5

    def test_gradients_match(self):
        mesh = create_mesh({"dp": 2, "sp": 4})
        key = jax.random.PRNGKey(1)
        B, T, H, D = 2, 16, 2, 8
        q, k, v = (
            jax.random.normal(jax.random.fold_in(key, i), (B, T, H, D), jnp.float32)
            for i in range(3)
        )
        for arg in range(3):
            g_ring = jax.grad(
                lambda *a: ring_attention(*a, mesh, causal=True).sum(), argnums=arg
            )(q, k, v)
            g_ref = jax.grad(
                lambda *a: reference_attention(*a, causal=True).sum(), argnums=arg
            )(q, k, v)
            assert float(jnp.abs(g_ring - g_ref).max()) < 1e-5, f"arg {arg}"

    @pytest.mark.parametrize("kv_chunk", [4, 8])
    def test_kv_chunking_is_exact(self, kv_chunk):
        """Chunked streaming (bounded score memory for long context) must be
        bit-for-bit exact vs the unchunked ring and the dense reference —
        forward and gradients."""
        mesh = create_mesh({"dp": 2, "sp": 4})
        key = jax.random.PRNGKey(3)
        B, T, H, D = 2, 32, 2, 8  # per-device kv block = 8
        q, k, v = (
            jax.random.normal(jax.random.fold_in(key, i), (B, T, H, D), jnp.float32)
            for i in range(3)
        )
        out = ring_attention(q, k, v, mesh, causal=True, kv_chunk=kv_chunk)
        ref = reference_attention(q, k, v, causal=True)
        assert float(jnp.abs(out - ref).max()) < 1e-5
        # gradients through the chunked inner scan, for ALL of q, k, v (the
        # dynamic_slice transpose path differs per argument)
        for arg in range(3):
            g_ring = jax.grad(
                lambda *a: ring_attention(*a, mesh, causal=True,
                                          kv_chunk=kv_chunk).sum(),
                argnums=arg,
            )(q, k, v)
            g_ref = jax.grad(
                lambda *a: reference_attention(*a, causal=True).sum(),
                argnums=arg,
            )(q, k, v)
            assert float(jnp.abs(g_ring - g_ref).max()) < 1e-5, f"arg {arg}"

    def test_kv_chunk_must_divide_block(self):
        mesh = create_mesh({"dp": 2, "sp": 4})
        q = jnp.ones((2, 32, 2, 8), jnp.float32)  # kv block = 8
        with pytest.raises(ValueError, match="must divide"):
            jax.block_until_ready(
                ring_attention(q, q, q, mesh, causal=True, kv_chunk=3)
            )

    def test_sp8_full_ring(self):
        mesh = create_mesh({"sp": 8})
        key = jax.random.PRNGKey(2)
        B, T, H, D = 1, 64, 2, 8
        q, k, v = (
            jax.random.normal(jax.random.fold_in(key, i), (B, T, H, D), jnp.float32)
            for i in range(3)
        )
        out = ring_attention(q, k, v, mesh, causal=True, batch_spec=(None,))
        ref = reference_attention(q, k, v, causal=True)
        assert float(jnp.abs(out - ref).max()) < 1e-5


class TestRingFlashAttention:
    """Custom-VJP ring (second-ring backward, no forward tape): exactness
    vs the dense reference and the autodiff ring, both block backends."""

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference_xla_blocks(self, causal):
        from tf_operator_tpu.parallel.ring_attention import (
            ring_flash_attention,
        )

        mesh = create_mesh({"dp": 2, "sp": 4})
        key = jax.random.PRNGKey(5)
        B, T, H, D = 2, 32, 4, 16
        q, k, v = (
            jax.random.normal(jax.random.fold_in(key, i), (B, T, H, D),
                              jnp.float32)
            for i in range(3)
        )
        out = ring_flash_attention(q, k, v, mesh, causal=causal,
                                   use_kernel=False)
        ref = reference_attention(q, k, v, causal=causal)
        assert float(jnp.abs(out - ref).max()) < 1e-5

    @pytest.mark.parametrize("causal", [True, False])
    def test_gradients_match_reference(self, causal):
        from tf_operator_tpu.parallel.ring_attention import (
            ring_flash_attention,
        )

        mesh = create_mesh({"dp": 2, "sp": 4})
        key = jax.random.PRNGKey(6)
        B, T, H, D = 2, 16, 2, 8
        q, k, v = (
            jax.random.normal(jax.random.fold_in(key, i), (B, T, H, D),
                              jnp.float32)
            for i in range(3)
        )
        for arg in range(3):
            g_ring = jax.grad(
                lambda *a: ring_flash_attention(
                    *a, mesh, causal=causal, use_kernel=False
                ).astype(jnp.float32).sum(),
                argnums=arg,
            )(q, k, v)
            g_ref = jax.grad(
                lambda *a: reference_attention(*a, causal=causal)
                .astype(jnp.float32).sum(),
                argnums=arg,
            )(q, k, v)
            assert float(jnp.abs(g_ring - g_ref).max()) < 1e-5, f"arg {arg}"

    def test_kernel_blocks_match_reference(self):
        """The Pallas-block path (interpret mode on CPU), fwd + grads: the
        per-device blocks must tile (seq/sp divisible by a legal block)."""
        from tf_operator_tpu.parallel.ring_attention import (
            ring_flash_attention,
        )

        mesh = create_mesh({"dp": 4, "sp": 2})
        key = jax.random.PRNGKey(7)
        B, T, H, D = 4, 64, 2, 8  # per-device block 32: tiles at 8/16/32
        q, k, v = (
            jax.random.normal(jax.random.fold_in(key, i), (B, T, H, D),
                              jnp.float32)
            for i in range(3)
        )
        out = ring_flash_attention(q, k, v, mesh, causal=True,
                                   use_kernel=True)
        ref = reference_attention(q, k, v, causal=True)
        assert float(jnp.abs(out - ref).max()) < 2e-5
        g_ring = jax.grad(
            lambda *a: ring_flash_attention(
                *a, mesh, causal=True, use_kernel=True
            ).astype(jnp.float32).sum()
        )(q, k, v)
        g_ref = jax.grad(
            lambda *a: reference_attention(*a, causal=True)
            .astype(jnp.float32).sum()
        )(q, k, v)
        assert float(jnp.abs(g_ring - g_ref).max()) < 2e-4

    def test_matches_autodiff_ring(self):
        """Both ring implementations agree (same sharded math, different
        backward strategies)."""
        from tf_operator_tpu.parallel.ring_attention import (
            ring_flash_attention,
        )

        mesh = create_mesh({"dp": 2, "sp": 4})
        key = jax.random.PRNGKey(8)
        B, T, H, D = 2, 32, 2, 8
        q, k, v = (
            jax.random.normal(jax.random.fold_in(key, i), (B, T, H, D),
                              jnp.float32)
            for i in range(3)
        )
        a = ring_attention(q, k, v, mesh, causal=True)
        b = ring_flash_attention(q, k, v, mesh, causal=True,
                                 use_kernel=False)
        assert float(jnp.abs(a - b).max()) < 1e-5


class TestUlyssesAttention:
    """All-to-all sequence parallelism (parallel/ulysses.py) — the second
    long-context strategy next to ring attention. Oracle: dense
    reference_attention (sharding is never a semantics change)."""

    def _qkv(self, B=2, T=64, H=8, D=16, seed=0):
        rng = np.random.default_rng(seed)
        return tuple(
            jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
            for _ in range(3)
        )

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, causal):
        from tf_operator_tpu.parallel.ulysses import ulysses_attention

        mesh = create_mesh({"sp": 4}, jax.devices()[:4])
        q, k, v = self._qkv()
        out = jax.jit(lambda q, k, v: ulysses_attention(
            q, k, v, mesh, causal=causal
        ))(q, k, v)
        ref = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )

    def test_composes_with_dp_and_tp(self):
        from tf_operator_tpu.parallel.ulysses import ulysses_attention

        mesh = create_mesh({"dp": 2, "sp": 2, "tp": 2})
        q, k, v = self._qkv(seed=1)
        out = jax.jit(lambda q, k, v: ulysses_attention(
            q, k, v, mesh, batch_spec=("dp",), head_spec=("tp",),
            causal=True,
        ))(q, k, v)
        ref = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )

    def test_gradients_match(self):
        from tf_operator_tpu.parallel.ulysses import ulysses_attention

        mesh = create_mesh({"sp": 4}, jax.devices()[:4])
        q, k, v = self._qkv(T=32, seed=2)

        g_u = jax.grad(lambda q: ulysses_attention(
            q, k, v, mesh, causal=True
        ).sum())(q)
        g_r = jax.grad(lambda q: reference_attention(
            q, k, v, causal=True
        ).sum())(q)
        np.testing.assert_allclose(
            np.asarray(g_u), np.asarray(g_r), atol=2e-4, rtol=2e-4
        )

    def test_rejects_indivisible_heads(self):
        from tf_operator_tpu.parallel.ulysses import ulysses_attention

        mesh = create_mesh({"sp": 4}, jax.devices()[:4])
        q, k, v = self._qkv(H=2)  # 2 heads, sp=4
        with pytest.raises(ValueError, match="local heads"):
            ulysses_attention(q, k, v, mesh, causal=True)
