"""Chaos soak: randomized pod kills across a fleet of concurrent jobs.

The reference carries a vestigial ``--chaos-level`` flag it never
implemented (cmd/tf-operator/app/options/options.go:41); this is that idea
done for real, at the O(100)-job design target's shape (~20 jobs, minutes
of randomized faults). A real TPUJobController runs against the in-memory
cluster; a fake kubelet advances pods; a chaos injector keeps killing
random running pods with retryable exit codes (plus two targeted permanent
faults). Afterwards the system must be CLEAN:

- every job terminal, with the expected terminal type,
- restart counters exactly equal to the injected fault count per job,
- zero wedged expectations, a drained workqueue,
- no leaked PDBs, no pods/services owned by vanished jobs.
"""

import random
import threading
import time

import pytest

from tf_operator_tpu.api import constants
from tf_operator_tpu.controller.jobcontroller import JobControllerConfig
from tf_operator_tpu.controller.tpujob_controller import TPUJobController
from tf_operator_tpu.runtime import objects
from tf_operator_tpu.runtime.client import ApiError
from tf_operator_tpu.runtime.kubeclient import KubeClusterClient, KubeConfig
from tf_operator_tpu.runtime.kubestub import KubeApiStub
from tf_operator_tpu.runtime.memcluster import InMemoryCluster
from tf_operator_tpu.scheduler import GangScheduler, SchedulerConfig
from tf_operator_tpu.scheduler.gang import (
    ANNOTATION_STATE,
    STATE_ADMITTED,
    STATE_QUEUED,
    is_gated,
)

import os

NUM_JOBS = int(os.environ.get("CHAOS_JOBS", "20"))
# CHAOS_SECONDS env: longer soaks for stability runs (e.g. 600 for a
# 10-minute window); default matches the CI slow tier's budget.
CHAOS_SECONDS = float(os.environ.get("CHAOS_SECONDS", "120"))
# Inject only into pods that have been Running at least this long, so the
# controller's informer has observed the Running phase before the kill —
# otherwise the restart happens but the counter can read low (the timing
# edge documented on the preemption test, commit 15593c7).
MIN_RUNNING_AGE = 0.8


def chaos_job(i: int) -> dict:
    """Jobs 0..14: plain 2-worker; 15..19: v4-8 slice jobs (2-host gang)."""
    worker: dict = {
        "restartPolicy": "ExitCode",
        "maxRestarts": 200,
        "template": {
            "spec": {
                "containers": [
                    {
                        "name": constants.DEFAULT_CONTAINER_NAME,
                        "image": "chaos/none",
                        "command": ["unused"],
                    }
                ]
            }
        },
    }
    if i >= 15:
        worker["tpu"] = {"acceleratorType": "v4-8"}  # 2 hosts, gang PDB
    else:
        worker["replicas"] = 2
    return {
        "apiVersion": constants.API_VERSION,
        "kind": constants.KIND,
        "metadata": {"name": f"chaos-{i}", "namespace": "default"},
        "spec": {"replicaSpecs": {"Worker": worker}},
    }


class ChaosKubelet(threading.Thread):
    """Pending → Running immediately; Running → Succeeded only once
    ``finish`` is set (pods stay alive during the chaos window so there is
    always something to kill)."""

    def __init__(self, client, stop, finish):
        super().__init__(daemon=True)
        self.client = client
        self.stop_event = stop
        self.finish = finish
        self.running_since: dict[str, float] = {}  # uid -> first-seen Running

    def run(self):
        while not self.stop_event.is_set():
            now = time.monotonic()
            for pod in list(self.client.list(objects.PODS, "default")):
                uid = objects.uid_of(pod)
                try:
                    phase = objects.pod_phase(pod)
                    if phase == objects.PENDING:
                        objects.set_pod_phase(pod, objects.RUNNING)
                        self.client.update_status(objects.PODS, pod)
                        self.running_since.setdefault(uid, now)
                    elif phase == objects.RUNNING:
                        self.running_since.setdefault(uid, now)
                        if self.finish.is_set():
                            objects.set_pod_phase(pod, objects.SUCCEEDED)
                            objects.set_container_terminated(
                                pod, constants.DEFAULT_CONTAINER_NAME, 0
                            )
                            self.client.update_status(objects.PODS, pod)
                except Exception:
                    continue  # conflict: next pass re-reads, kubelet-style
            time.sleep(0.05)


class ChaosInjector(threading.Thread):
    """Kills one running pod of a random job per tick (exit 137, retryable).

    One in-flight fault per job: the next injection into a job waits until
    the previously killed pod incarnation is gone, so each successful
    injection is exactly one restart event — making the final counters
    exactly assertable. Two designated jobs additionally get one PERMANENT
    fault (exit 1) late in the window."""

    def __init__(self, client, kubelet: ChaosKubelet, stop, seed=7):
        super().__init__(daemon=True)
        self.client = client
        self.kubelet = kubelet
        self.stop_event = stop
        self.rng = random.Random(seed)
        self.injected: dict[str, int] = {}  # job -> retryable faults landed
        self.in_flight: dict[str, str] = {}  # job -> killed pod uid
        self.permanent_targets = {"chaos-3", "chaos-17"}
        self.permanent_done: set[str] = set()
        self.started_at = time.monotonic()

    def _fault(self, pod, code: int) -> bool:
        try:
            objects.set_pod_phase(pod, objects.FAILED)
            objects.set_container_terminated(
                pod, constants.DEFAULT_CONTAINER_NAME, code
            )
            self.client.update_status(objects.PODS, pod)
            return True
        except Exception:
            return False  # conflict: injection did not land; don't count

    def run(self):
        while not self.stop_event.is_set():
            time.sleep(self.rng.uniform(0.1, 0.4))
            pods = list(self.client.list(objects.PODS, "default"))
            by_job: dict[str, list] = {}
            uids = set()
            for p in pods:
                uids.add(objects.uid_of(p))
                job = objects.labels_of(p).get(constants.LABEL_JOB_NAME)
                if job:
                    by_job.setdefault(job, []).append(p)
            # Clear in-flight markers whose pod incarnation is gone.
            for job, uid in list(self.in_flight.items()):
                if uid not in uids:
                    del self.in_flight[job]
            candidates = [
                j for j in by_job
                if j not in self.in_flight and j not in self.permanent_done
            ]
            if not candidates:
                continue
            job = self.rng.choice(candidates)
            now = time.monotonic()
            running = [
                p for p in by_job[job]
                if objects.pod_phase(p) == objects.RUNNING
                and now - self.kubelet.running_since.get(
                    objects.uid_of(p), now
                ) >= MIN_RUNNING_AGE
            ]
            if not running:
                continue
            pod = self.rng.choice(running)
            # Permanent fault for the designated jobs, once, late in the
            # window (after they have absorbed some retryable chaos).
            elapsed = time.monotonic() - self.started_at
            if (
                job in self.permanent_targets
                and elapsed > CHAOS_SECONDS * 0.6
            ):
                if self._fault(pod, 1):  # exit 1: permanent under ExitCode
                    self.permanent_done.add(job)
                continue
            if self._fault(pod, 137):  # SIGKILL: retryable
                self.injected[job] = self.injected.get(job, 0) + 1
                self.in_flight[job] = objects.uid_of(pod)


def terminal_type(job) -> str | None:
    for cond in job.get("status", {}).get("conditions", []):
        if cond["type"] in ("Succeeded", "Failed") and cond["status"] == "True":
            return cond["type"]
    return None


@pytest.mark.slow
def test_chaos_soak_converges_clean():
    client = InMemoryCluster()
    controller = TPUJobController(
        client,
        JobControllerConfig(
            reconcile_period=0.3, informer_resync=1.0, threadiness=4
        ),
    )
    stop = threading.Event()
    finish = threading.Event()
    threading.Thread(target=controller.run, args=(stop,), daemon=True).start()
    kubelet = ChaosKubelet(client, stop, finish)
    kubelet.start()
    stop_injecting = threading.Event()
    injector = ChaosInjector(client, kubelet, stop_injecting)
    try:
        for i in range(NUM_JOBS):
            client.create(objects.TPUJOBS, chaos_job(i))
        time.sleep(2.0)  # fleet comes up
        injector.start()
        time.sleep(CHAOS_SECONDS)
        stop_injecting.set()  # injector only; the system runs on
        injector.join(timeout=5)
        time.sleep(1.0)
        finish.set()  # kubelet now completes surviving/recreated pods

        deadline = time.monotonic() + 180
        jobs = []
        while time.monotonic() < deadline:
            jobs = client.list(objects.TPUJOBS, "default")
            if all(terminal_type(j) is not None for j in jobs):
                break
            time.sleep(0.5)
        states = {objects.name_of(j): terminal_type(j) for j in jobs}
        stuck = [n for n, s in states.items() if s is None]
        assert not stuck, f"jobs never terminal after chaos: {stuck}"

        # Terminal types: permanent-faulted jobs Failed, everything else
        # recovered to Succeeded.
        for name, state in states.items():
            if name in injector.permanent_done:
                assert state == "Failed", f"{name}: {state}"
            else:
                assert state == "Succeeded", f"{name}: {state}"

        # Restart counters exactly match the injected retryable faults.
        mismatches = {}
        total_faults = 0
        for j in jobs:
            name = objects.name_of(j)
            want = injector.injected.get(name, 0)
            got = int(j.get("status", {}).get("restartCount", 0))
            total_faults += want
            if got != want:
                mismatches[name] = (want, got)
        assert not mismatches, f"restartCount != injected: {mismatches}"
        assert total_faults >= NUM_JOBS, (
            f"chaos window too quiet ({total_faults} faults) — not a soak"
        )

        # Workqueue drains (resync re-enqueues; poll for an empty moment).
        drained = False
        drain_deadline = time.monotonic() + 15
        while time.monotonic() < drain_deadline:
            if len(controller.queue) == 0:
                drained = True
                break
            time.sleep(0.05)
        assert drained, f"workqueue never drained ({len(controller.queue)})"

        # No wedged expectations.
        exp = controller.expectations
        wedged = [k for k in list(exp._store) if not exp.satisfied(k)]
        assert not wedged, f"wedged expectations: {wedged}"

        # No leaked gang PDBs once every job is terminal.
        pdbs = client.list(objects.PDBS, "default")
        assert not pdbs, f"leaked PDBs: {[objects.name_of(p) for p in pdbs]}"

        # Every surviving pod/service belongs to an existing job.
        live_jobs = {objects.name_of(j) for j in jobs}
        for kind in (objects.PODS, objects.SERVICES):
            for obj in client.list(kind, "default"):
                owner = objects.labels_of(obj).get(constants.LABEL_JOB_NAME)
                assert owner in live_jobs, (
                    f"orphaned {kind} {objects.name_of(obj)} (job {owner})"
                )

        print(
            f"\nchaos: {NUM_JOBS} jobs, {CHAOS_SECONDS:.0f}s window, "
            f"{total_faults} retryable faults + "
            f"{len(injector.permanent_done)} permanent, all terminal, "
            f"counters exact, no leaks"
        )
    finally:
        stop.set()
        time.sleep(0.5)


# ===========================================================================
# Gang-admission chaos (fast tier): the all-or-nothing proofs of ISSUE 1.
#
# Invariant under test — the deadlock gang admission exists to prevent: a
# job must never have a strict subset of its slice pods Running while the
# remainder CANNOT run (still gated). Both cluster backends are exercised:
# the in-memory store directly, and the wire-level Kubernetes stub through
# KubeClusterClient (gate enforcement surfacing as HTTP 422).
# ===========================================================================

GANG_CAPACITY = {"v4": (2, 2, 2)}  # exactly one v4-8 gang (8 chips) fits


def gang_job(name: str, priority_class: str | None = None) -> dict:
    spec: dict = {
        "replicaSpecs": {
            "Worker": {
                "tpu": {"acceleratorType": "v4-8"},  # 2 hosts, one slice
                "template": {
                    "spec": {
                        "containers": [
                            {
                                "name": constants.DEFAULT_CONTAINER_NAME,
                                "image": "chaos/none",
                                "command": ["unused"],
                            }
                        ]
                    }
                },
            }
        }
    }
    if priority_class:
        spec["scheduling"] = {"priorityClass": priority_class}
    return {
        "apiVersion": constants.API_VERSION,
        "kind": constants.KIND,
        "metadata": {"name": name, "namespace": "default"},
        "spec": spec,
    }


@pytest.fixture(params=["memcluster", "kubestub"])
def gang_backend(request):
    """(client, store, stub|None): the controller-facing client plus the
    authoritative InMemoryCluster behind it (rejection counters)."""
    if request.param == "memcluster":
        store = InMemoryCluster()
        yield store, store, None
        return
    stub = KubeApiStub()
    stub.start()
    try:
        yield KubeClusterClient(KubeConfig(server=stub.url)), stub.cluster, stub
    finally:
        stub.stop()


def gang_controller(client, scheduler):
    from tf_operator_tpu.runtime.events import FakeRecorder

    return TPUJobController(
        client,
        JobControllerConfig(reconcile_period=0.2),
        recorder=FakeRecorder(),
        scheduler=scheduler,
    )


def sync(tc, key: str):
    tc.job_informer.sync_now()
    tc.pod_informer.sync_now()
    tc.service_informer.sync_now()
    return tc.sync_job(key)


def job_pods(store, name: str) -> list[dict]:
    return store.list(
        objects.PODS, "default", {constants.LABEL_JOB_NAME: name}
    )


def running_count(store, name: str) -> int:
    return sum(
        1 for p in job_pods(store, name)
        if objects.pod_phase(p) == objects.RUNNING
    )


class PartialSliceWatch(threading.Thread):
    """Continuously samples the store asserting the gang invariant: a job
    with any Running pod has NO gated pod left (its whole slice became
    runnable as a unit)."""

    def __init__(self, store, job_names):
        super().__init__(daemon=True)
        self.store = store
        self.job_names = job_names
        self.stop_event = threading.Event()
        self.violations: list[str] = []

    def run(self):
        while not self.stop_event.is_set():
            for name in self.job_names:
                pods = job_pods(self.store, name)
                running = [
                    p for p in pods
                    if objects.pod_phase(p) == objects.RUNNING
                ]
                gated = [p for p in pods if is_gated(p)]
                if running and gated:
                    self.violations.append(
                        f"{name}: {len(running)} Running while "
                        f"{len(gated)} still gated"
                    )
            time.sleep(0.002)


def hammer_running(client, store, name: str, seconds: float) -> int:
    """A rogue kubelet: keeps trying to mark every pod of ``name`` Running.
    Returns how many attempts the backend REFUSED (gate enforcement)."""
    rejected = 0
    deadline = time.monotonic() + seconds
    while time.monotonic() < deadline:
        for pod in job_pods(store, name):
            fresh = dict(pod)
            objects.set_pod_phase(fresh, objects.RUNNING)
            try:
                client.update_status(objects.PODS, fresh)
            except ApiError:
                rejected += 1
        time.sleep(0.01)
    return rejected


@pytest.mark.scheduler
def test_gang_crash_between_create_and_release_never_runs_partial(
    gang_backend,
):
    """Controller dies after creating the gang's pods but BEFORE lifting the
    gates: nothing may run (a fake kubelet hammering Running is refused by
    the store / by HTTP 422), and a successor controller finishes the
    release so the whole slice becomes runnable together."""
    client, store, stub = gang_backend

    # Controller #1 whose release path "crashes": admission is decided and
    # persisted, pods are created gated, but the gates never lift.
    sched1 = GangScheduler(config=SchedulerConfig(capacity=GANG_CAPACITY))
    tc1 = gang_controller(client, sched1)
    sched1.release_gang = lambda job: False  # the simulated crash point
    client.create(objects.TPUJOBS, gang_job("half-born"))

    watch = PartialSliceWatch(store, ["half-born"])
    watch.start()
    try:
        sync(tc1, "default/half-born")
        pods = job_pods(store, "half-born")
        assert len(pods) == 2 and all(is_gated(p) for p in pods)
        ann = store.get(objects.TPUJOBS, "default", "half-born")[
            "metadata"]["annotations"]
        assert ann[ANNOTATION_STATE] == STATE_ADMITTED  # persisted FIRST

        # The rogue kubelet cannot run any gated pod.
        rejected = hammer_running(client, store, "half-born", 0.25)
        assert rejected > 0, "gate was never actually exercised"
        assert running_count(store, "half-born") == 0
        assert store.gate_rejections > 0
        if stub is not None:
            assert stub.gate_422s_served > 0  # enforced AT THE WIRE

        # Controller #1 is gone; a fresh incarnation recovers the persisted
        # admission and finishes the release — no re-arbitration, no
        # re-queue, and the slice flips runnable as one unit.
        sched2 = GangScheduler(config=SchedulerConfig(capacity=GANG_CAPACITY))
        tc2 = gang_controller(client, sched2)
        sync(tc2, "default/half-born")
        pods = job_pods(store, "half-born")
        assert pods and all(not is_gated(p) for p in pods)

        # Now the kubelet succeeds — the whole gang runs.
        hammer_running(client, store, "half-born", 0.1)
        assert running_count(store, "half-born") == 2
    finally:
        watch.stop_event.set()
        watch.join(timeout=2)
    assert not watch.violations, watch.violations


@pytest.mark.scheduler
def test_gang_oversubscription_preempts_within_one_epoch(gang_backend):
    """Two jobs oversubscribe the fleet (capacity fits exactly one): the
    low-priority gang runs; a critical gang arrives and — within ONE
    reconcile pass — evicts the victim whole, takes its place, and runs.
    At no instant does either job hold a strict subset of runnable pods."""
    client, store, stub = gang_backend
    sched = GangScheduler(config=SchedulerConfig(capacity=GANG_CAPACITY))
    tc = gang_controller(client, sched)

    watch = PartialSliceWatch(store, ["meek", "boss"])
    watch.start()
    try:
        client.create(objects.TPUJOBS, gang_job("meek", "low"))
        sync(tc, "default/meek")
        hammer_running(client, store, "meek", 0.1)
        assert running_count(store, "meek") == 2  # victim fully up

        # The critical job lands. One reconcile epoch later it has evicted
        # the victim gang WHOLE and owns the slice.
        client.create(objects.TPUJOBS, gang_job("boss", "critical"))
        sync(tc, "default/boss")
        assert job_pods(store, "meek") == [], "victim evicted whole"
        boss_pods = job_pods(store, "boss")
        assert len(boss_pods) == 2 and all(not is_gated(p) for p in boss_pods)
        meek_ann = store.get(objects.TPUJOBS, "default", "meek")[
            "metadata"]["annotations"]
        assert meek_ann[ANNOTATION_STATE] == STATE_QUEUED  # requeued as gang
        snap = sched.snapshot()
        assert [g["key"] for g in snap["admitted"]] == ["default/boss"]
        assert [g["key"] for g in snap["queued"]] == ["default/meek"]

        hammer_running(client, store, "boss", 0.1)
        assert running_count(store, "boss") == 2
        # The preempted gang cannot creep back while capacity is held: a
        # later sync of the victim creates nothing.
        sync(tc, "default/meek")
        assert job_pods(store, "meek") == []
    finally:
        watch.stop_event.set()
        watch.join(timeout=2)
    assert not watch.violations, watch.violations
