"""Exit-code policy, naming, and logger utilities (tier-1 parity:
pkg/util/train/train_util_test.go, util_test.go)."""

import pytest

from tf_operator_tpu.api.helpers import (
    gen_labels,
    labels_to_selector,
    replica_labels,
    selector_matches,
)
from tf_operator_tpu.utils import exit_codes, logger, names


class TestExitCodes:
    @pytest.mark.parametrize("code", [1, 2, 126, 127, 128, 134, 139])
    def test_permanent(self, code):
        # 134 = SIGABRT (XLA/runtime aborts) and 139 = SIGSEGV are app
        # bugs despite being >128: enumerated in _PERMANENT_SIGNAL_EXITS.
        assert exit_codes.is_permanent(code)
        assert not exit_codes.is_retryable(code)

    @pytest.mark.parametrize("code", [130, 137, 138, 143])
    def test_retryable(self, code):
        assert exit_codes.is_retryable(code)

    def test_success(self):
        assert exit_codes.is_success(0)
        assert not exit_codes.is_retryable(0)
        assert not exit_codes.is_permanent(0)

    def test_unknown_signal_retryable(self):
        assert exit_codes.is_retryable(131)  # SIGQUIT

    def test_sigusr1_reserved(self):
        assert exit_codes.SIGUSR1_EXIT == 138
        assert exit_codes.is_retryable(138)


class TestNames:
    def test_gen_name(self):
        assert names.gen_name("mnist", "Worker", 3) == "mnist-worker-3"

    def test_gen_name_sanitizes(self):
        assert names.gen_name("My_Job", "PS", 0) == "my-job-ps-0"

    def test_rand_string_charset(self):
        s = names.rand_string(64)
        assert len(s) == 64
        assert all(c.islower() or c.isdigit() for c in s)


class TestLabels:
    def test_replica_labels(self):
        labels = replica_labels("j1", "Worker", 2)
        assert labels["tpu-replica-type"] == "worker"
        assert labels["tpu-replica-index"] == "2"
        assert labels["tpu-job-name"] == "j1"

    def test_selector(self):
        sel = gen_labels("j1")
        assert selector_matches(sel, replica_labels("j1", "PS", 0))
        assert not selector_matches(sel, replica_labels("j2", "PS", 0))
        assert "tpu-job-name=j1" in labels_to_selector(sel)


class TestLogger:
    def test_fields_bound(self, capsys):
        logger.configure(json_format=True)
        log = logger.for_replica("ns", "job", "Worker")
        log.info("hello")
        err = capsys.readouterr().err
        assert '"job": "ns.job"' in err
        assert '"replica_type": "Worker"' in err
        logger.configure(json_format=False)
