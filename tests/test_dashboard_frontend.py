"""Dashboard frontend smoke (parity: the reference's React CreateJob /
CreateReplicaSpec / EnvVarCreator / VolumeCreator forms, dashboard/frontend/
src/components).

No JS engine ships in CI, so the smoke asserts the contract between app.js
and the backend instead of pixel output: every API route the SPA calls must
exist server-side, the slice-picker catalog must carry real topology data,
the create flow's 422 path must surface a message, and the JS must be
delimiter-balanced (catches truncated/garbled edits).
"""

import json
import os
import re
import urllib.error
import urllib.request

import pytest

FRONTEND = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tf_operator_tpu", "dashboard", "frontend",
)


def fetch(base, path, method="GET", body=None):
    req = urllib.request.Request(
        base + path, method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Content-Type": "application/json"} if body is not None else {},
    )
    try:
        with urllib.request.urlopen(req, timeout=5) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


@pytest.fixture(scope="module")
def dashboard():
    # The shared operator fixture runs without --dashboard; spawn our own.
    import subprocess, sys, socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(FRONTEND)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-m", "tf_operator_tpu.cli.operator",
         "--serve", str(port), "--dashboard", "--reconcile-period", "0.3"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    base = f"http://127.0.0.1:{port}"
    import time as _t
    deadline = _t.monotonic() + 15
    while _t.monotonic() < deadline:
        try:
            fetch(base, "/tpujobs/api/tpujob")
            break
        except (urllib.error.URLError, ConnectionError):
            if proc.poll() is not None:
                raise RuntimeError("operator died")
            _t.sleep(0.2)
    yield base
    proc.terminate()
    proc.wait(timeout=5)


def test_static_assets_served(dashboard):
    for path, marker in (
        ("/tpujobs/", b"TPU Job Operator"),
        ("/app.js", b"replicaSpecCard"),  # index.html loads root-relative
        ("/style.css", b".replica-spec"),
    ):
        code, body = fetch(dashboard, path)
        assert code == 200 and marker in body, path


def test_app_js_routes_exist_server_side(dashboard):
    """Every api("...") literal in app.js must resolve to a live backend
    route (route drift between SPA and backend fails here)."""
    src = open(os.path.join(FRONTEND, "app.js")).read()
    routes = set(re.findall(r'api\(\s*[`"]([^`"$]+)[`"]', src))
    routes |= {
        tmpl.replace("${ns}", "default").replace("${name}", "nosuch")
        .replace("${podName}", "nosuch-pod")
        for tmpl in re.findall(r'api\(\s*`([^`]+)`', src)
    }
    assert routes, "no api() calls found in app.js"
    for route in routes:
        # Fill any residual template params with dummies.
        path = re.sub(r"\$\{[^}]+\}", "default", route)
        code, _ = fetch(dashboard, "/tpujobs/api" + path)
        # 200 = live; 404 with JSON error = handled NotFound (e.g. missing
        # job); anything falling through to the SPA (HTML) means the route
        # does not exist server-side.
        assert code in (200, 404), (route, code)
        if code == 404:
            _, body = fetch(dashboard, "/tpujobs/api" + path)
            assert body.lstrip()[:1] == b"{", f"route {route} fell through to SPA"


def test_detail_view_renders_replica_statuses():
    """The detail view's replica-set table reads the status fields the
    controller actually writes (field drift between status engine and SPA
    fails here)."""
    src = open(os.path.join(FRONTEND, "app.js")).read()
    # Detail-specific markers (the list view's replicaSummary also reads
    # these fields, so scope to the jobDetailView additions).
    assert '"Replica sets"' in src
    detail = src[src.index("async function jobDetailView"):
                 src.index("async function showLogs")]
    assert "replicaStatuses" in detail
    for field in ("active", "succeeded", "failed"):
        assert f"s.{field}" in detail, field
    assert "job.status?.restartCount" in detail  # job-level restart readout
    assert '"Role", "Active", "Succeeded", "Failed"' in detail


def test_accelerator_catalog_backs_slice_picker(dashboard):
    code, body = fetch(dashboard, "/tpujobs/api/accelerators")
    assert code == 200
    items = json.loads(body)["items"]
    by_type = {i["acceleratorType"]: i for i in items}
    assert by_type["v5e-16"]["topology"] == "4x4"
    assert by_type["v5e-16"]["numHosts"] == 4
    assert by_type["v5e-16"]["multiHost"] is True
    assert by_type["v5e-4"]["numHosts"] == 1
    # every entry resolvable by the controller's own topology code
    from tf_operator_tpu.topology import slices

    for item in items:
        topo = slices.resolve(item["acceleratorType"], item["topology"])
        assert topo.num_hosts == item["numHosts"]


def test_create_rejection_surfaces_message(dashboard):
    """The form's error path: POSTing an invalid job returns 422 + message
    (rendered into #create-error by the SPA)."""
    bad = {
        "apiVersion": "tpuflow.org/v1", "kind": "TPUJob",
        "metadata": {"name": "bad", "namespace": "default"},
        "spec": {"replicaSpecs": {"Worker": {"template": {"spec": {
            "containers": [{"name": "not-tensorflow", "image": "x"}]}}}}},
    }
    code, body = fetch(dashboard, "/tpujobs/api/tpujob", "POST", bad)
    assert code == 422
    msg = json.loads(body)
    assert msg.get("message"), msg


def test_clone_flow_wiring(dashboard):
    """Clone/resubmit (round-4 dashboard polish): the detail view links to
    #/clone/{ns}/{name}, the router fetches the source job and opens the
    create form prefilled, and every spec field the form writes is also
    read back on prefill (write/read drift fails here)."""
    src = open(os.path.join(FRONTEND, "app.js")).read()
    # Detail view offers the clone deep link.
    detail = src[src.index("async function jobDetailView"):
                 src.index("async function showLogs")]
    assert "#/clone/" in detail
    # Router handles it by fetching the job and prefilling the form.
    router = src[src.index("async function route"):]
    assert '"clone"' in router
    assert "createView(d.tpujob)" in router
    # Prefill reads every field the submit path writes.
    create = src[src.index("async function createView"):
                 src.index("// ---------- router")]
    for field in ("cleanPodPolicy", "ttlSecondsAfterFinished",
                  "scheduling", "replicaSpecs"):
        assert f"prefill?.spec?.{field}" in create or (
            f"prefill.spec.{field}" in create
        ) or f"spec?.{field}" in create, field
    card = src[src.index("function replicaSpecCard"):
               src.index("async function createView")]
    for marker in ("init.replicas", "c0.image", "c0.command", "c0.env",
                   "init.restartPolicy", "init.tpu", "volumeMounts"):
        assert marker in card, marker


def test_create_form_args_resources_roundtrip(dashboard):
    """Round-5 create-form depth (reference parity: CreateReplicaSpec's
    args + gpuCount fields, generalized to requests/limits): a job POSTed
    exactly as the form's buildJob() emits it must pass validation and
    the launched pods must inherit args and resources verbatim."""
    import time as _t

    job = {
        "apiVersion": "tpuflow.org/v1", "kind": "TPUJob",
        "metadata": {"name": "form-depth", "namespace": "default"},
        "spec": {"cleanPodPolicy": "Running", "replicaSpecs": {"Worker": {
            "replicas": 1, "restartPolicy": "Never",
            "template": {"spec": {"containers": [{
                "name": "tensorflow", "image": "tpu-operator/test-server",
                "command": ["python", "train.py"],
                "args": ["--steps", "100"],
                "resources": {
                    "requests": {"cpu": "500m", "memory": "1Gi"},
                    "limits": {"cpu": "1", "memory": "2Gi"},
                },
            }]}}}}},
    }
    code, body = fetch(dashboard, "/tpujobs/api/tpujob", "POST", job)
    assert code in (200, 201), body
    try:
        deadline = _t.monotonic() + 10
        pods = []
        while _t.monotonic() < deadline and not pods:
            code, body = fetch(
                dashboard, "/tpujobs/api/tpujob/default/form-depth")
            assert code == 200
            pods = json.loads(body).get("pods", [])
            _t.sleep(0.3)
        assert pods, "controller never created pods"
        c = pods[0]["spec"]["containers"][0]
        assert c["args"] == ["--steps", "100"]
        assert c["resources"]["requests"] == {"cpu": "500m",
                                              "memory": "1Gi"}
        assert c["resources"]["limits"] == {"cpu": "1", "memory": "2Gi"}
    finally:
        fetch(dashboard, "/tpujobs/api/tpujob/default/form-depth", "DELETE")


def test_create_form_depth_wiring():
    """The form writes args/resources and both preview and deploy share
    one builder (what you preview is what gets POSTed); prefill reads
    back every new field (clone drift fails here)."""
    src = open(os.path.join(FRONTEND, "app.js")).read()
    card = src[src.index("function replicaSpecCard"):
               src.index("async function createView")]
    for marker in ("c0.args", "container.args", "c0.resources",
                   "container.resources", "requests", "limits"):
        assert marker in card, marker
    create = src[src.index("async function createView"):
                 src.index("// ---------- router")]
    assert "buildJob" in create
    assert "manifest-preview" in create
    # The submit path and the preview path both call the shared builder.
    assert create.count("buildJob()") >= 2


def test_detail_view_renders_volumes():
    """The volumes card (reference-parity detail field): one row per
    (role, volume) with hostPath source and container mount paths."""
    src = open(os.path.join(FRONTEND, "app.js")).read()
    detail = src[src.index("async function jobDetailView"):
                 src.index("async function showLogs")]
    assert '"Volumes"' in detail
    assert "volumeMounts" in detail
    assert "hostPath" in detail
    assert '"Role", "Volume", "Source", "Mounts"' in detail


def test_app_js_delimiters_balanced():
    """Cheap parse sanity: braces/brackets/parens balance outside strings,
    comments, and regex-free template literals."""
    src = open(os.path.join(FRONTEND, "app.js")).read()
    stack = []
    pairs = {")": "(", "]": "[", "}": "{"}
    i, n = 0, len(src)
    mode = None  # None | "'" | '"' | "`" | "//" | "/*"
    while i < n:
        c = src[i]
        nxt = src[i + 1] if i + 1 < n else ""
        if mode is None:
            if c in "\"'`":
                mode = c
            elif c == "/" and nxt == "/":
                mode = "//"
            elif c == "/" and nxt == "*":
                mode = "/*"
            elif c in "([{":
                stack.append(c)
            elif c in ")]}":
                assert stack and stack[-1] == pairs[c], f"unbalanced {c} at {i}"
                stack.pop()
        elif mode in ("'", '"', "`"):
            if c == "\\":
                i += 1
            elif c == mode:
                mode = None
        elif mode == "//" and c == "\n":
            mode = None
        elif mode == "/*" and c == "*" and nxt == "/":
            mode = None
            i += 1
        i += 1
    assert not stack, f"unclosed delimiters: {stack}"
    assert mode is None, f"unterminated {mode}"
