"""The continuous-batching serving loop: threaded mixed traffic stays
bit-exact, EOS retires early, drain answers every socket (in-flight
finishes, queued 503s), metrics move, and the serve bench emits its
BENCH line (structural asserts only — no wall-clock in any assert)."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tf_operator_tpu.models.transformer import (
    Transformer,
    TransformerConfig,
    generate,
)
from tf_operator_tpu.runtime.metrics import (
    SERVE_REQUESTS_TOTAL,
    SERVE_TOKENS_TOTAL,
    SERVE_TTFT_SECONDS,
)
from tf_operator_tpu.serve.engine import ContinuousEngine
from tf_operator_tpu.serve.scheduler import (
    ContinuousScheduler,
    ServeRequest,
    ShuttingDown,
)

pytestmark = pytest.mark.serve

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_layers=2, n_heads=2, d_ff=64,
    max_seq_len=64, dtype=jnp.float32,
)


@pytest.fixture(scope="module")
def params():
    return Transformer(CFG).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]


def prompt_of(p: int, seed: int) -> np.ndarray:
    return np.random.default_rng(seed).integers(
        0, CFG.vocab_size, (1, p)
    ).astype(np.int32)


def solo(params, prompt, steps, *, temperature=0.0, top_p=None, seed=0):
    kw = {}
    if temperature > 0:
        kw = dict(temperature=temperature, rng=jax.random.PRNGKey(seed))
        if top_p is not None:
            kw["top_p"] = top_p
    return np.asarray(
        generate(CFG, params, jnp.asarray(prompt), steps, **kw)
    )


def test_threaded_mixed_traffic_bit_exact(params):
    """Concurrent mixed-shape greedy AND sampled requests through the
    loop (chunked prefill interleaved) all reproduce their solo
    outputs; the registry counters advance by the served amounts."""
    ok_before = SERVE_REQUESTS_TOTAL.value(outcome="ok")
    tokens_before = SERVE_TOKENS_TOTAL.value()
    ttft_before = SERVE_TTFT_SECONDS.snapshot()
    engine = ContinuousEngine(CFG, params, max_slots=4, prefill_chunk=4)
    sched = ContinuousScheduler(engine, prefill_tokens_per_step=8).start()
    reqs = [
        (prompt_of(4, 1), 8, 0.0, None, 0),
        (prompt_of(7, 2), 6, 0.0, None, 0),
        (prompt_of(3, 3), 10, 0.9, None, 11),
        (prompt_of(5, 4), 5, 0.7, 0.8, 7),
        (prompt_of(9, 5), 4, 0.0, None, 0),
        (prompt_of(6, 6), 12, 0.0, None, 0),
    ]
    results: dict[int, np.ndarray] = {}

    def client(i):
        prompt, steps, t, tp, seed = reqs[i]
        results[i] = sched.submit(
            prompt, steps, temperature=t, top_p=tp, seed=seed
        )

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(len(reqs))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    try:
        total = 0
        for i, (prompt, steps, t, tp, seed) in enumerate(reqs):
            want = solo(params, prompt, steps, temperature=t, top_p=tp,
                        seed=seed)
            np.testing.assert_array_equal(results[i], want,
                                          err_msg=f"request {i}")
            total += steps
        assert engine.decode_step_compiles == engine.warmup_compiles
        assert SERVE_REQUESTS_TOTAL.value(outcome="ok") == (
            ok_before + len(reqs)
        )
        assert SERVE_TOKENS_TOTAL.value() == tokens_before + total
        ttft_count = sum(
            c - b for c, b in zip(SERVE_TTFT_SECONDS.snapshot(),
                                  ttft_before)
        )
        assert ttft_count == len(reqs)
        assert 0.0 < sched.mean_occupancy <= 1.0
    finally:
        sched.stop(timeout=30)


def test_eos_retires_slot_early(params):
    """A request carrying eos_id stops at the EOS token (inclusive) and
    frees its slot for the next request."""
    engine = ContinuousEngine(CFG, params, max_slots=1)
    sched = ContinuousScheduler(engine).start()
    try:
        prompt = prompt_of(5, 42)
        want = solo(params, prompt, 10)[0]
        eos = int(want[3])
        out = sched.submit(prompt, 10, eos_id=eos)
        k = list(want).index(eos)
        np.testing.assert_array_equal(out[0], want[:k + 1])
        # The slot freed: a follow-up request runs on the single slot.
        out2 = sched.submit(prompt, 4)
        np.testing.assert_array_equal(out2[0], want[:4])
    finally:
        sched.stop(timeout=30)


def test_drain_finishes_inflight_rejects_queued(params):
    """The SIGTERM/eviction drain contract: the admitted request
    finishes its full decode, the queued one (no slot — max_slots=1)
    fails fast with ShuttingDown, and post-stop submits are refused."""
    rejected_before = SERVE_REQUESTS_TOTAL.value(outcome="rejected")
    engine = ContinuousEngine(CFG, params, max_slots=1)
    sched = ContinuousScheduler(engine).start()
    inflight: dict = {}
    queued: dict = {}

    def first():
        try:
            inflight["out"] = sched.submit(prompt_of(4, 1), 40)
        except Exception as exc:  # noqa: BLE001
            inflight["err"] = exc

    def second():
        try:
            queued["out"] = sched.submit(prompt_of(4, 2), 4)
        except Exception as exc:  # noqa: BLE001
            queued["err"] = exc

    t1 = threading.Thread(target=first)
    t1.start()
    # Deterministic trigger: wait until the first request owns the slot.
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and engine.active_slots < 1:
        time.sleep(0.01)
    assert engine.active_slots == 1
    t2 = threading.Thread(target=second)
    t2.start()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and sched.queue_depth < 1:
        time.sleep(0.01)
    sched.stop(timeout=60)
    t1.join(timeout=30)
    t2.join(timeout=30)
    assert inflight.get("out") is not None and inflight["out"].shape == (
        1, 40,
    ), inflight
    assert isinstance(queued.get("err"), ShuttingDown), queued
    with pytest.raises(ShuttingDown):
        sched.submit(prompt_of(4, 3), 2)
    assert SERVE_REQUESTS_TOTAL.value(outcome="rejected") >= (
        rejected_before + 1
    )
    # The drained output is still exact.
    np.testing.assert_array_equal(
        inflight["out"], solo(params, prompt_of(4, 1), 40)
    )


def test_submit_validates_eagerly(params):
    engine = ContinuousEngine(CFG, params, max_slots=1)
    sched = ContinuousScheduler(engine)  # no loop needed: all eager
    with pytest.raises(ValueError, match="max_seq_len"):
        sched.submit(prompt_of(60, 1), 10)
    with pytest.raises(ValueError, match="top_p"):
        sched.submit(prompt_of(4, 1), 2, top_p=0.9)
    with pytest.raises(ValueError, match="one request row"):
        ServeRequest(np.zeros((2, 4), np.int32), 2)


def test_debug_snapshot_shape(params):
    engine = ContinuousEngine(CFG, params, max_slots=2)
    sched = ContinuousScheduler(engine).start()
    try:
        sched.submit(prompt_of(4, 1), 3)
        snap = sched.debug_snapshot()
        for key in ("engine", "max_slots", "active_slots", "queue_depth",
                    "decode_step_compiles", "tokens_generated",
                    "requests_done", "mean_occupancy", "ttft_p50_s",
                    "draining", "kv_cache"):
            assert key in snap, key
        assert snap["engine"] == "continuous"
        assert snap["requests_done"] >= 1
        # The block-pool stats ride the snapshot (paged is the default).
        assert snap["kv_cache"]["mode"] == "paged"
        assert snap["kv_cache"]["blocks_total"] > 0
    finally:
        sched.stop(timeout=30)


def test_traced_request_bit_identical_with_spans_and_no_recompile(params):
    """The tier-1 tracing pin: with the data-plane tracer ON (the
    default), a request through a real engine (paged + chunked prefix
    so the span set is maximal) produces bit-identical output, zero
    post-warmup recompiles, a queue→prefill→decode span chain under its
    request id, and a per-request timing breakdown that adds up."""
    from tf_operator_tpu.runtime.tracing import SERVE_TRACER

    SERVE_TRACER.clear()
    assert SERVE_TRACER.enabled  # tracing on by default — that IS the pin
    engine = ContinuousEngine(CFG, params, max_slots=2, kv_paged=True,
                              kv_block=8, prefill_chunk=4)
    sched = ContinuousScheduler(engine).start()
    try:
        prompt = prompt_of(11, 21)
        want = solo(params, prompt, 12)
        req = sched.submit_request(
            ServeRequest(prompt, 12, request_id="traced-req-1")
        )
        assert np.array_equal(
            np.asarray(req.out, np.int32).reshape(1, -1), want
        )
        assert engine.decode_step_compiles == engine.warmup_compiles

        mine = [s for s in SERVE_TRACER.spans()
                if s.attrs.get("request_id") == "traced-req-1"]
        names = [s.name for s in mine]
        assert "queue.wait" in names
        assert "admit.plan" in names
        assert "prefill.chunk" in names or "prefill.join" in names
        assert "decode.interval" in names
        # Parentage-by-time: the request's phases are ordered and the
        # decode interval aggregates steps (never one span per token).
        start_of = {s.name: s.start_us for s in mine}
        assert start_of["queue.wait"] <= start_of["admit.plan"]
        assert start_of["admit.plan"] <= min(
            s.start_us for s in mine if s.name.startswith("prefill")
        )
        decode = [s for s in mine if s.name == "decode.interval"]
        assert sum(int(s.attrs["tokens"]) for s in decode) == 12
        assert len(decode) < 12

        t = req.timing()
        assert t["request_id"] == "traced-req-1"
        assert t["decode_ms"] > 0 and t["prefill_ms"] > 0
        assert t["itl_mean_ms"] >= 0 and len(req.itl_values()) == 11
    finally:
        sched.stop(timeout=30.0)


@pytest.mark.slow
def test_serve_bench_spec_structural():
    """tools/serve_bench.py --engine spec (BENCH_SMOKE): the ISSUE-15
    triple — spec continuous engine vs plain continuous vs legacy
    --spec-k coalesce on one seeded decode-heavy schedule with a
    quick-trained target/draft pair. Structural pins: all three legs
    decode the IDENTICAL token count (same greedy schedule, same
    trained model), zero errors, the spec engine's two round
    executables frozen from warmup, a high measured accept_rate (the
    draft genuinely rode — without it the comparison is meaningless),
    and the spec line beating the legacy coalesce path outright. The
    spec/continuous ratio is asserted only as populated-and-sane here:
    the >1 acceptance number is the full-size bench line's (smoke
    shapes shrink horizons until round quantization eats the margin);
    BENCH_r* rounds carry the real ratios."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_SMOKE="1",
               PALLAS_AXON_POOL_IPS="")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools",
                                      "serve_bench.py"),
         "--engine", "spec", "--requests", "10"],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    lines = [json.loads(raw) for raw in proc.stdout.splitlines()
             if raw.startswith("{")]
    by_metric = {line["metric"]: line for line in lines}
    spec = by_metric["serve_spec_tokens_per_sec_mixed"]
    cont = by_metric["serve_continuous_tokens_per_sec_mixed"]
    legacy = by_metric["serve_spec_coalesce_tokens_per_sec_mixed"]
    assert spec["errors"] == cont["errors"] == legacy["errors"] == 0
    assert (spec["generated_tokens"] == cont["generated_tokens"]
            == legacy["generated_tokens"] > 0)
    assert spec["requests"] == 10
    # One draft + one verify executable, frozen from warmup.
    assert spec["decode_step_compiles"] == spec["warmup_compiles"]
    assert spec["spec_k"] >= 1 and spec["spec_rounds"] > 0
    # The draft rode: the quick-trained pair accepts most proposals.
    assert spec["accept_rate"] > 0.5, spec
    assert spec["tokens_per_lane_round"] > 1.5, spec
    # Ratios populated; the legacy lock-step path is beaten outright
    # even at smoke shapes (the engine keeps occupancy the coalescer
    # structurally cannot).
    assert spec["vs_spec_coalesce"] > 1.0, spec
    assert spec["vs_baseline"] > 0.5, spec


def test_serve_bench_emits_structural_line():
    """tools/serve_bench.py (BENCH_SMOKE shapes): both legs emit JSON,
    token counts agree across engines (same seeded schedule, greedy —
    the legs decode the same work), zero errors, zero post-warmup
    recompiles; the capacity mix shows the paged cache admitting >= 2x
    the dense layout's concurrent long-context requests at the SAME
    byte budget, with nonzero prefill-tokens-saved. Timing fields are
    present but never asserted."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_SMOKE="1",
               PALLAS_AXON_POOL_IPS="")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools",
                                      "serve_bench.py"),
         "--requests", "8"],
        capture_output=True, text=True, timeout=420, env=env,
        cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    lines = [json.loads(raw) for raw in proc.stdout.splitlines()
             if raw.startswith("{")]
    by_metric = {line["metric"]: line for line in lines}
    cont = by_metric["serve_continuous_tokens_per_sec_mixed"]
    coal = by_metric["serve_coalesce_tokens_per_sec_mixed"]
    assert cont["errors"] == 0 and coal["errors"] == 0
    assert cont["generated_tokens"] == coal["generated_tokens"] > 0
    assert cont["requests"] == coal["requests"] == 8
    assert cont["decode_step_compiles"] == 1
    assert 0.0 < cont["mean_occupancy"] <= 1.0
    assert cont["vs_baseline"] > 0  # the ratio line is populated
    for key in ("ttft_p50_ms", "ttft_p99_ms", "steady_occupancy",
                "itl_p50_ms", "itl_p99_ms"):
        assert key in cont, key
    # Both engines report ITL (the ROADMAP item-2 interference pin's
    # baseline); the continuous engine's comes from real decode-step
    # gaps, so under load it must be a positive number.
    assert cont["itl_p99_ms"] > 0
    assert "itl_p50_ms" in coal and "itl_p99_ms" in coal
    # The capacity mix: paged vs dense at one byte budget.
    paged = by_metric["serve_paged_longctx_tokens_per_sec_mixed"]
    dense = by_metric["serve_dense_longctx_tokens_per_sec_mixed"]
    assert paged["errors"] == 0 and dense["errors"] == 0
    assert paged["generated_tokens"] == dense["generated_tokens"] > 0
    assert paged["kv"] == "paged" and dense["kv"] == "dense"
    # The ROADMAP item-2 claim, asserted: the SAME bytes admit >= 2x the
    # concurrent long-context requests once rows are block-paged.
    assert paged["admitted_concurrency"] >= 2 * dense[
        "admitted_concurrency"
    ], (paged, dense)
    assert paged["prefill_tokens_saved"] > 0
    assert paged["decode_step_compiles"] == paged["warmup_compiles"]
    assert paged["vs_baseline"] > 0 and paged["admitted_ratio"] >= 2.0
