"""Kubernetes adapter: contract suite + config resolution + controller E2E.

The contract suite runs identically against two ClusterClient backends —
the in-memory cluster and KubeClusterClient talking HTTP to the K8s
wire-protocol stub — proving the adapter preserves the semantics the
controller stack depends on (uid/RV assignment, optimistic concurrency,
status subresource isolation, selector lists, watch streams). The reference
gets the same guarantee from client-go fakes (tfcontroller_test.go:63-64);
here the fake sits across a real HTTP boundary.
"""

import json
import os
import time

import pytest

from tf_operator_tpu.runtime import objects
from tf_operator_tpu.runtime.client import (
    ADDED,
    DELETED,
    MODIFIED,
    AlreadyExists,
    Conflict,
    NotFound,
)
from tf_operator_tpu.runtime.kubeclient import (
    KubeClusterClient,
    KubeConfig,
    KubeConfigError,
    in_cluster_config,
    load_kubeconfig,
    resolve_config,
)
from tf_operator_tpu.runtime.kubestub import KubeApiStub, parse_k8s_path
from tf_operator_tpu.runtime.memcluster import InMemoryCluster


# ---------------------------------------------------------------------------
# Shared backends
# ---------------------------------------------------------------------------

@pytest.fixture(params=["mem", "kube"])
def backend(request):
    """Yields (client, teardown-handled) for each backend under contract."""
    if request.param == "mem":
        yield InMemoryCluster()
        return
    stub = KubeApiStub()
    stub.start()
    client = KubeClusterClient(KubeConfig(server=stub.url))
    yield client
    stub.stop()


def pod(name, ns="default", labels=None):
    return objects.new_pod(name, ns, labels=labels)


# ---------------------------------------------------------------------------
# The contract suite
# ---------------------------------------------------------------------------

class TestContract:
    def test_create_assigns_identity(self, backend):
        created = backend.create(objects.PODS, pod("p1"))
        assert created["metadata"]["uid"]
        assert created["metadata"]["resourceVersion"]
        assert created["metadata"]["creationTimestamp"]

    def test_create_duplicate_raises_already_exists(self, backend):
        backend.create(objects.PODS, pod("p1"))
        with pytest.raises(AlreadyExists):
            backend.create(objects.PODS, pod("p1"))

    def test_get_roundtrip_and_not_found(self, backend):
        backend.create(objects.PODS, pod("p1", labels={"role": "w"}))
        got = backend.get(objects.PODS, "default", "p1")
        assert got["metadata"]["labels"] == {"role": "w"}
        with pytest.raises(NotFound):
            backend.get(objects.PODS, "default", "absent")

    def test_list_by_namespace_and_selector(self, backend):
        backend.create(objects.PODS, pod("a", "ns1", labels={"app": "x"}))
        backend.create(objects.PODS, pod("b", "ns1", labels={"app": "y"}))
        backend.create(objects.PODS, pod("c", "ns2", labels={"app": "x"}))
        assert len(backend.list(objects.PODS)) == 3
        assert [objects.name_of(o) for o in backend.list(objects.PODS, "ns1")] == [
            "a",
            "b",
        ]
        sel = backend.list(objects.PODS, "ns1", {"app": "x"})
        assert [objects.name_of(o) for o in sel] == ["a"]

    def test_update_conflicts_on_stale_rv(self, backend):
        backend.create(objects.PODS, pod("p1"))
        v1 = backend.get(objects.PODS, "default", "p1")
        v2 = backend.get(objects.PODS, "default", "p1")
        v2["status"]["phase"] = "Running"
        backend.update(objects.PODS, v2)
        v1["status"]["phase"] = "Failed"
        with pytest.raises(Conflict):
            backend.update(objects.PODS, v1)

    def test_update_status_touches_only_status(self, backend):
        backend.create(objects.PODS, pod("p1", labels={"keep": "me"}))
        obj = backend.get(objects.PODS, "default", "p1")
        obj["metadata"]["labels"] = {"hacked": "yes"}
        obj["status"] = {"phase": "Running"}
        backend.update_status(objects.PODS, obj)
        after = backend.get(objects.PODS, "default", "p1")
        assert after["metadata"]["labels"] == {"keep": "me"}
        assert after["status"]["phase"] == "Running"

    def test_update_bumps_resource_version(self, backend):
        backend.create(objects.PODS, pod("p1"))
        before = backend.get(objects.PODS, "default", "p1")
        before["status"]["phase"] = "Running"
        after = backend.update(objects.PODS, before)
        assert int(after["metadata"]["resourceVersion"]) > int(
            before["metadata"]["resourceVersion"]
        )

    def test_patch_merge(self, backend):
        backend.create(objects.PODS, pod("p1", labels={"a": "1"}))
        patched = backend.patch_merge(
            objects.PODS,
            "default",
            "p1",
            {"metadata": {"labels": {"b": "2"}}},
        )
        assert patched["metadata"]["labels"] == {"a": "1", "b": "2"}

    def test_delete_then_not_found(self, backend):
        backend.create(objects.PODS, pod("p1"))
        backend.delete(objects.PODS, "default", "p1")
        with pytest.raises(NotFound):
            backend.get(objects.PODS, "default", "p1")
        with pytest.raises(NotFound):
            backend.delete(objects.PODS, "default", "p1")

    def test_crd_kind_roundtrip(self, backend):
        # A schema-valid job: the kube stub enforces TPUJob admission by
        # default, as a real cluster with deploy/crd.yaml applied would.
        from tf_operator_tpu.utils import testutil

        job = testutil.new_tpujob(name="j1", worker=1).to_dict()
        backend.create(objects.TPUJOBS, job)
        got = backend.get(objects.TPUJOBS, "default", "j1")
        assert got["spec"]["replicaSpecs"]["Worker"]["replicas"] == 1
        got["status"] = {"conditions": [{"type": "Created", "status": "True"}]}
        backend.update_status(objects.TPUJOBS, got)
        after = backend.get(objects.TPUJOBS, "default", "j1")
        assert after["status"]["conditions"][0]["type"] == "Created"

    def test_watch_delivers_add_modify_delete(self, backend):
        watch = backend.watch(objects.PODS, "default")
        # kube watch threads need a beat to connect before events flow.
        time.sleep(0.3)
        backend.create(objects.PODS, pod("w1"))
        obj = backend.get(objects.PODS, "default", "w1")
        obj["status"]["phase"] = "Running"
        backend.update(objects.PODS, obj)
        backend.delete(objects.PODS, "default", "w1")

        seen = []
        deadline = time.monotonic() + 5
        while len(seen) < 3 and time.monotonic() < deadline:
            ev = watch.next(timeout=0.5)
            if ev is not None:
                seen.append(ev)
        assert [e.type for e in seen] == [ADDED, MODIFIED, DELETED]
        assert all(objects.name_of(e.object) == "w1" for e in seen)
        backend.stop_watch(watch)

    def test_watch_namespace_scoping(self, backend):
        watch = backend.watch(objects.PODS, "ns1")
        time.sleep(0.3)
        backend.create(objects.PODS, pod("other", "ns2"))
        backend.create(objects.PODS, pod("mine", "ns1"))
        ev = watch.next(timeout=5)
        assert ev is not None and objects.name_of(ev.object) == "mine"
        backend.stop_watch(watch)


# ---------------------------------------------------------------------------
# Kube-specific behavior
# ---------------------------------------------------------------------------

class TestKubeSpecific:
    def test_watch_reconnects_after_stream_drop(self):
        stub = KubeApiStub()
        stub.start()
        client = KubeClusterClient(KubeConfig(server=stub.url))
        try:
            watch = client.watch(objects.PODS, "default")
            time.sleep(0.3)
            client.create(objects.PODS, pod("before"))
            assert watch.next(timeout=5) is not None
            # Sever the live stream; the client must reconnect and keep
            # delivering events (resourceVersion resume path).
            resp = getattr(watch, "_resp", None)
            assert resp is not None
            resp.close()
            time.sleep(1.5)  # reconnect backoff
            client.create(objects.PODS, pod("after"))
            deadline = time.monotonic() + 5
            got = None
            while time.monotonic() < deadline:
                ev = watch.next(timeout=0.5)
                if ev is not None and objects.name_of(ev.object) == "after":
                    got = ev
                    break
            assert got is not None, "watch did not resume after stream drop"
            client.stop_watch(watch)
        finally:
            stub.stop()

    def test_path_mapping(self):
        cfg = KubeConfig(server="https://example:6443")
        c = KubeClusterClient(cfg)
        assert c._collection(objects.PODS, "ns1") == "/api/v1/namespaces/ns1/pods"
        assert (
            c._collection(objects.PDBS, "ns1")
            == "/apis/policy/v1/namespaces/ns1/poddisruptionbudgets"
        )
        assert (
            c._collection(objects.LEASES, "kube-system")
            == "/apis/coordination.k8s.io/v1/namespaces/kube-system/leases"
        )
        assert (
            c._collection(objects.TPUJOBS, "default")
            == "/apis/tpuflow.org/v1/namespaces/default/tpujobs"
        )
        assert c._collection(objects.TPUJOBS, None) == "/apis/tpuflow.org/v1/tpujobs"
        assert c._collection(objects.NAMESPACES, None) == "/api/v1/namespaces"

    def test_stub_path_parser(self):
        r = parse_k8s_path("/api/v1/namespaces/ns1/pods/p1/status")
        assert (r.kind, r.namespace, r.name, r.subresource) == (
            "pods",
            "ns1",
            "p1",
            "status",
        )
        r = parse_k8s_path("/apis/tpuflow.org/v1/tpujobs")
        assert (r.kind, r.namespace, r.name) == ("tpujobs", None, None)
        r = parse_k8s_path("/api/v1/namespaces")
        assert (r.kind, r.namespace, r.name) == ("namespaces", None, None)
        assert parse_k8s_path("/healthz") is None


# ---------------------------------------------------------------------------
# Config resolution
# ---------------------------------------------------------------------------

KUBECONFIG_YAML = """\
apiVersion: v1
kind: Config
current-context: dev
contexts:
- name: dev
  context: {{cluster: devcluster, user: devuser}}
- name: prod
  context: {{cluster: prodcluster, user: produser}}
clusters:
- name: devcluster
  cluster:
    server: https://dev.example:6443
    insecure-skip-tls-verify: true
- name: prodcluster
  cluster:
    server: https://prod.example:6443
    certificate-authority-data: {ca_b64}
users:
- name: devuser
  user: {{token: devtoken}}
- name: produser
  user:
    client-certificate-data: {cert_b64}
    client-key-data: {key_b64}
"""


class TestConfig:
    def _write(self, tmp_path):
        import base64

        pem = base64.b64encode(b"-----BEGIN CERTIFICATE-----\nfake\n").decode()
        text = KUBECONFIG_YAML.format(ca_b64=pem, cert_b64=pem, key_b64=pem)
        path = tmp_path / "kubeconfig"
        path.write_text(text)
        return str(path)

    def test_load_current_context(self, tmp_path):
        cfg = load_kubeconfig(self._write(tmp_path))
        assert cfg.server == "https://dev.example:6443"
        assert cfg.bearer_token() == "devtoken"
        assert cfg.insecure_skip_tls_verify

    def test_load_named_context_with_cert_data(self, tmp_path):
        cfg = load_kubeconfig(self._write(tmp_path), context="prod")
        assert cfg.server == "https://prod.example:6443"
        assert cfg.ca_data and b"CERTIFICATE" in cfg.ca_data
        assert cfg.client_cert_data and cfg.client_key_data

    def test_kubeconfig_env_fallback(self, tmp_path, monkeypatch):
        path = self._write(tmp_path)
        monkeypatch.setenv("KUBECONFIG", path)
        cfg = load_kubeconfig()
        assert cfg.server == "https://dev.example:6443"

    def test_missing_file_raises(self, tmp_path):
        with pytest.raises(KubeConfigError):
            load_kubeconfig(str(tmp_path / "nope"))

    def test_token_file(self, tmp_path):
        tf = tmp_path / "token"
        tf.write_text("filetoken\n")
        cfg = KubeConfig(server="https://x", token_file=str(tf))
        assert cfg.bearer_token() == "filetoken"

    def test_in_cluster_config(self, tmp_path, monkeypatch):
        (tmp_path / "token").write_text("sa-token")
        (tmp_path / "ca.crt").write_text("ca-pem")
        monkeypatch.setenv("KUBERNETES_SERVICE_HOST", "10.0.0.1")
        monkeypatch.setenv("KUBERNETES_SERVICE_PORT", "443")
        cfg = in_cluster_config(sa_dir=str(tmp_path))
        assert cfg.server == "https://10.0.0.1:443"
        assert cfg.bearer_token() == "sa-token"
        assert cfg.ca_file == str(tmp_path / "ca.crt")

    def test_in_cluster_missing_ca_raises(self, tmp_path, monkeypatch):
        (tmp_path / "token").write_text("sa-token")  # token but no ca.crt
        monkeypatch.setenv("KUBERNETES_SERVICE_HOST", "10.0.0.1")
        with pytest.raises(KubeConfigError, match="CA bundle"):
            in_cluster_config(sa_dir=str(tmp_path))

    def test_relative_paths_resolve_against_kubeconfig_dir(self, tmp_path):
        (tmp_path / "ca.crt").write_text("pem")
        (tmp_path / "kc").write_text(
            "current-context: c\n"
            "contexts: [{name: c, context: {cluster: cl, user: u}}]\n"
            "clusters: [{name: cl, cluster: {server: 'https://x:6443', "
            "certificate-authority: ca.crt}}]\n"
            "users: [{name: u, user: {tokenFile: token}}]\n"
        )
        cfg = load_kubeconfig(str(tmp_path / "kc"))
        assert cfg.ca_file == str(tmp_path / "ca.crt")
        assert cfg.token_file == str(tmp_path / "token")

    def test_in_cluster_outside_cluster_raises(self, monkeypatch):
        monkeypatch.delenv("KUBERNETES_SERVICE_HOST", raising=False)
        with pytest.raises(KubeConfigError):
            in_cluster_config(sa_dir="/definitely/not/mounted")

    def test_resolve_falls_back_to_kubeconfig(self, tmp_path, monkeypatch):
        monkeypatch.delenv("KUBERNETES_SERVICE_HOST", raising=False)
        cfg = resolve_config(self._write(tmp_path))
        assert cfg.server == "https://dev.example:6443"


# ---------------------------------------------------------------------------
# Exec credential plugins (the GKE auth path: gke-gcloud-auth-plugin shape)
# ---------------------------------------------------------------------------

# A stock `gcloud container clusters get-credentials` kubeconfig: exec block,
# no static token/cert (reference gets this via client-go's exec authenticator;
# k8sutil.go:52-76 just loads the config and inherits the auth stack).
GKE_KUBECONFIG_YAML = """\
apiVersion: v1
kind: Config
current-context: gke
contexts:
- name: gke
  context: {{cluster: gkecluster, user: gkeuser}}
clusters:
- name: gkecluster
  cluster:
    server: https://34.0.0.1
    certificate-authority-data: {ca_b64}
users:
- name: gkeuser
  user:
    exec:
      apiVersion: client.authentication.k8s.io/v1beta1
      command: {command}
      args: {args}
      env:
      - name: PLUGIN_MODE
        value: test
      provideClusterInfo: true
      installHint: Install gke-gcloud-auth-plugin for use with kubectl
      interactiveMode: IfAvailable
"""

PLUGIN_SCRIPT = """\
import json, os, sys, time

count_file = {count_file!r}
n = 1
if count_file:
    try:
        n = int(open(count_file).read()) + 1
    except (OSError, ValueError):
        n = 1
    open(count_file, "w").write(str(n))

# Record the ExecCredential request object for protocol assertions.
info_file = {info_file!r}
if info_file:
    open(info_file, "w").write(os.environ.get("KUBERNETES_EXEC_INFO", ""))

status = {{"token": "minted-%d" % n}}
expiry_s = {expiry_s!r}
if expiry_s is not None:
    status["expirationTimestamp"] = time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime(time.time() + expiry_s)
    )
print(json.dumps({{
    "apiVersion": "client.authentication.k8s.io/v1beta1",
    "kind": "ExecCredential",
    "status": status,
}}))
"""


class TestExecCredential:
    def _gke_kubeconfig(self, tmp_path, command, args):
        import base64
        import json as _json

        ca = base64.b64encode(b"-----BEGIN CERTIFICATE-----\nfake\n").decode()
        path = tmp_path / "gke-kubeconfig"
        path.write_text(
            GKE_KUBECONFIG_YAML.format(
                ca_b64=ca, command=command, args=_json.dumps(args)
            )
        )
        return str(path)

    def _plugin(self, tmp_path, count_file=None, info_file=None, expiry_s=None):
        import sys

        script = tmp_path / "fake_auth_plugin.py"
        script.write_text(
            PLUGIN_SCRIPT.format(
                count_file=count_file, info_file=info_file, expiry_s=expiry_s
            )
        )
        return sys.executable, [str(script)]

    def test_gke_shaped_kubeconfig_loads(self, tmp_path):
        cmd, args = self._plugin(tmp_path)
        cfg = load_kubeconfig(self._gke_kubeconfig(tmp_path, cmd, args))
        assert cfg.exec_config is not None
        assert cfg.exec_config.command == cmd
        assert cfg.exec_config.provide_cluster_info
        assert "gke-gcloud-auth-plugin" in cfg.exec_config.install_hint
        assert cfg.exec_config.env == {"PLUGIN_MODE": "test"}
        assert cfg.exec_config.cluster_info["server"] == "https://34.0.0.1"

    def test_minted_token_and_exec_info_protocol(self, tmp_path):
        info_file = str(tmp_path / "exec_info.json")
        cmd, args = self._plugin(tmp_path, info_file=info_file)
        cfg = load_kubeconfig(self._gke_kubeconfig(tmp_path, cmd, args))
        assert cfg.bearer_token() == "minted-1"
        info = json.loads(open(info_file).read())
        assert info["kind"] == "ExecCredential"
        assert info["spec"]["interactive"] is False
        # provideClusterInfo forwards the cluster block to the plugin.
        assert info["spec"]["cluster"]["server"] == "https://34.0.0.1"

    def test_token_cached_until_expiry(self, tmp_path):
        count_file = str(tmp_path / "count")
        cmd, args = self._plugin(tmp_path, count_file=count_file, expiry_s=3600)
        cfg = load_kubeconfig(self._gke_kubeconfig(tmp_path, cmd, args))
        assert cfg.bearer_token() == "minted-1"
        assert cfg.bearer_token() == "minted-1"  # cached, no re-exec
        assert open(count_file).read() == "1"

    def test_near_expiry_token_is_reminted(self, tmp_path):
        count_file = str(tmp_path / "count")
        # 10s expiry < the 120s refresh margin: every call re-mints.
        cmd, args = self._plugin(tmp_path, count_file=count_file, expiry_s=10)
        cfg = load_kubeconfig(self._gke_kubeconfig(tmp_path, cmd, args))
        assert cfg.bearer_token() == "minted-1"
        assert cfg.bearer_token() == "minted-2"

    def test_authenticates_against_token_requiring_stub(self, tmp_path):
        cmd, args = self._plugin(tmp_path)
        cfg = load_kubeconfig(self._gke_kubeconfig(tmp_path, cmd, args))
        cfg.server = None  # replaced below; TLS off for the HTTP stub
        stub = KubeApiStub()
        stub.required_token = "minted-1"
        stub.start()
        try:
            cfg.server = stub.url
            cfg.ca_data = None
            cfg.ca_file = None
            client = KubeClusterClient(cfg)
            client.create(objects.PODS, pod("authed"))
            assert client.get(objects.PODS, "default", "authed")
        finally:
            stub.stop()

    def test_401_triggers_remint_and_retry(self, tmp_path):
        count_file = str(tmp_path / "count")
        cmd, args = self._plugin(tmp_path, count_file=count_file)
        cfg = load_kubeconfig(self._gke_kubeconfig(tmp_path, cmd, args))
        stub = KubeApiStub()
        stub.required_token = "minted-1"
        stub.start()
        try:
            cfg.server = stub.url
            cfg.ca_data = None
            cfg.ca_file = None
            client = KubeClusterClient(cfg)
            client.create(objects.PODS, pod("p1"))
            # Server-side rotation: old token now rejected with 401. The
            # client must re-mint (plugin run #2) and retry transparently.
            stub.required_token = "minted-2"
            client.create(objects.PODS, pod("p2"))
            assert open(count_file).read() == "2"
        finally:
            stub.stop()

    def test_missing_plugin_reports_install_hint(self, tmp_path):
        path = self._gke_kubeconfig(
            tmp_path, "definitely-not-on-path-gke-plugin", []
        )
        cfg = load_kubeconfig(path)
        with pytest.raises(KubeConfigError, match="Install gke-gcloud-auth"):
            cfg.bearer_token()

    def test_cert_credentials_unsupported(self, tmp_path):
        script = tmp_path / "certplugin.py"
        script.write_text(
            "import json\n"
            "print(json.dumps({'apiVersion': "
            "'client.authentication.k8s.io/v1beta1',\n"
            "  'kind': 'ExecCredential',\n"
            "  'status': {'clientCertificateData': 'PEM', "
            "'clientKeyData': 'PEM'}}))\n"
        )
        import sys

        cfg = load_kubeconfig(
            self._gke_kubeconfig(tmp_path, sys.executable, [str(script)])
        )
        with pytest.raises(KubeConfigError, match="client-certificate"):
            cfg.bearer_token()

    def test_plugin_failure_surfaces_stderr(self, tmp_path):
        script = tmp_path / "failplugin.py"
        script.write_text(
            "import sys; print('boom: no creds', file=sys.stderr); "
            "sys.exit(3)\n"
        )
        import sys

        cfg = load_kubeconfig(
            self._gke_kubeconfig(tmp_path, sys.executable, [str(script)])
        )
        with pytest.raises(KubeConfigError, match="boom: no creds"):
            cfg.bearer_token()

    def test_legacy_auth_provider_still_rejected(self, tmp_path):
        path = tmp_path / "kc"
        path.write_text(
            "current-context: c\n"
            "contexts: [{name: c, context: {cluster: cl, user: u}}]\n"
            "clusters: [{name: cl, cluster: {server: 'https://x:6443'}}]\n"
            "users: [{name: u, user: {auth-provider: {name: gcp}}}]\n"
        )
        with pytest.raises(KubeConfigError, match="auth-provider"):
            load_kubeconfig(str(path))


# ---------------------------------------------------------------------------
# client-go-grade list/watch robustness
# ---------------------------------------------------------------------------

class TestListWatchRobustness:
    def test_list_paginates_with_limit_and_continue(self):
        stub = KubeApiStub()
        stub.start()
        try:
            client = KubeClusterClient(
                KubeConfig(server=stub.url), list_page_size=7
            )
            for i in range(23):
                client.create(objects.PODS, pod(f"p{i:02d}"))
            stub.list_pages_served = 0
            got = client.list(objects.PODS, "default")
            assert len(got) == 23
            assert {objects.name_of(o) for o in got} == {
                f"p{i:02d}" for i in range(23)
            }
            assert stub.list_pages_served == 4  # ceil(23/7)
        finally:
            stub.stop()

    def test_expired_continue_token_falls_back_to_full_list(self):
        """client-go reflector behavior: 410 on a continue token → one
        unpaginated list, not a page-1 restart that could expire forever."""
        stub = KubeApiStub()
        stub.start()
        try:
            client = KubeClusterClient(
                KubeConfig(server=stub.url), list_page_size=4
            )
            for i in range(10):
                client.create(objects.PODS, pod(f"c{i}"))
            stub.expire_continue_tokens = True
            got = client.list(objects.PODS, "default")
            assert len(got) == 10  # fallback delivered the whole collection
        finally:
            stub.stop()

    def test_list_pagination_disabled_with_zero_page_size(self):
        stub = KubeApiStub()
        stub.start()
        try:
            client = KubeClusterClient(
                KubeConfig(server=stub.url), list_page_size=0
            )
            for i in range(5):
                client.create(objects.PODS, pod(f"q{i}"))
            stub.list_pages_served = 0
            assert len(client.list(objects.PODS, "default")) == 5
            assert stub.list_pages_served == 0  # single unpaginated GET
        finally:
            stub.stop()

    def test_watch_server_side_timeout_reconnects(self):
        stub = KubeApiStub()
        stub.start()
        try:
            client = KubeClusterClient(
                KubeConfig(server=stub.url), watch_timeout_seconds=1.0
            )
            w = client.watch(objects.PODS, "default")
            time.sleep(0.3)  # let the stream connect before the first event
            client.create(objects.PODS, pod("w1"))
            e1 = w.next(timeout=5.0)
            assert e1 is not None and objects.name_of(e1.object) == "w1"
            # Outlive at least one server-side stream budget (1s), then
            # prove events still flow on the reconnected stream. The stub
            # streams from "now" (no history replay), so a create landing
            # exactly in a reconnect gap is lost — keep creating fresh pods
            # until one arrives rather than betting on a single create.
            time.sleep(2.5)
            deadline = time.monotonic() + 15.0
            seen = set()
            i = 0
            while time.monotonic() < deadline and not seen & {
                f"w2-{j}" for j in range(i + 1)
            }:
                client.create(objects.PODS, pod(f"w2-{i}"))
                i += 1
                e = w.next(timeout=1.0)
                if e is not None:
                    seen.add(objects.name_of(e.object))
            assert any(n.startswith("w2-") for n in seen)
            client.stop_watch(w)
        finally:
            stub.stop()

    def test_bookmarks_advance_rv_without_surfacing_events(self):
        """BOOKMARK events (apiserver RV checkpoints on idle streams) must
        be consumed internally — advancing the resume RV so a long-idle
        watch never resumes from a compacted RV — and never delivered to
        the consumer as object events."""
        stub = KubeApiStub()
        stub.send_bookmarks = True
        stub.start()
        try:
            client = KubeClusterClient(
                KubeConfig(server=stub.url), watch_timeout_seconds=2.0
            )
            w = client.watch(objects.PODS, "default")
            time.sleep(0.3)
            client.create(objects.PODS, pod("bm1"))
            e = w.next(timeout=5.0)
            assert e is not None and objects.name_of(e.object) == "bm1"
            # Idle across several bookmark ticks AND a server-side stream
            # budget: bump the store RV via another namespace (invisible
            # to this namespaced watch), let bookmarks carry it, and
            # compact everything below it. If the client resumed from its
            # last EVENT RV instead of the bookmark RV, the reconnect
            # would 410 and relist; with bookmarks it reconnects cleanly.
            for i in range(5):
                client.create(objects.PODS, pod(f"other-{i}", "elsewhere"))
            time.sleep(1.5)  # bookmarks flow on the idle stream
            stub.expire_watch_rv_below = int(stub.cluster.current_rv)
            time.sleep(2.5)  # outlive the 2s budget: reconnect happens
            # The stub streams from "now" (no history replay), so a single
            # create can land in a reconnect gap and be lost — keep
            # creating fresh pods until one arrives (same pattern as the
            # server-timeout test; a real apiserver replays from the
            # resumed RV so this is purely a stub artifact).
            deadline = time.monotonic() + 10.0
            seen = []
            i = 0
            while time.monotonic() < deadline and not any(
                n.startswith("bm2-") for n in seen
            ):
                client.create(objects.PODS, pod(f"bm2-{i}"))
                i += 1
                e = w.next(timeout=1.0)
                if e is not None:
                    seen.append(objects.name_of(e.object))
            # No BOOKMARK leaked through as an event, and the stream
            # survived the idle + compaction + reconnect cycle.
            assert any(n.startswith("bm2-") for n in seen), (
                f"stream did not survive: {seen}"
            )
            assert all(n.startswith("bm") for n in seen), seen
            # The headline behavior: the bookmark-advanced RV reconnected
            # CLEANLY — the client never needed the 410-relist fallback
            # (which would also converge, masking a bookmark regression).
            assert stub.watch_410s_served == 0, (
                f"{stub.watch_410s_served} watch resumes hit 410: bookmarks "
                "did not advance the resume RV"
            )
            client.stop_watch(w)
        finally:
            stub.stop()

    def test_killed_stream_with_missed_delete_and_410_converges(self):
        """The client-go-reflector scenario: the watch connection dies
        without a FIN, a DELETE happens during the gap, and the resume RV
        has been compacted away (410). The informer must converge — deleted
        object gone from cache, new events flowing — with no wedged thread."""
        from tf_operator_tpu.controller.informer import Informer
        import threading as _threading

        stub = KubeApiStub()
        stub.start()
        stop = _threading.Event()
        try:
            client = KubeClusterClient(
                KubeConfig(server=stub.url), watch_timeout_seconds=30.0
            )
            inf = Informer(client, objects.PODS, "default", resync_period=0.5)
            inf.start(stop)
            client.create(objects.PODS, pod("keep"))
            client.create(objects.PODS, pod("doomed"))
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline and (
                inf.get("default", "doomed") is None
                or inf.get("default", "keep") is None
            ):
                time.sleep(0.05)
            assert inf.get("default", "doomed") is not None

            # Sever the stream abruptly; delete during the gap; compact the
            # resume RV so the reconnect gets 410 and must relist.
            assert stub.kill_watches() >= 1
            client.delete(objects.PODS, "default", "doomed")
            stub.expire_watch_rv_below = int(stub.cluster.current_rv)

            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline and (
                inf.get("default", "doomed") is not None
            ):
                time.sleep(0.1)
            assert inf.get("default", "doomed") is None, "missed DELETE never repaired"
            assert inf.get("default", "keep") is not None

            # The watch thread survived: a fresh ADDED still arrives.
            client.create(objects.PODS, pod("after-recovery"))
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline and (
                inf.get("default", "after-recovery") is None
            ):
                time.sleep(0.05)
            assert inf.get("default", "after-recovery") is not None
        finally:
            stop.set()
            stub.stop()


# ---------------------------------------------------------------------------
# Deploy manifests + CLI wiring
# ---------------------------------------------------------------------------

class TestDeployManifests:
    def test_crd_schema_matches_api_types(self):
        import yaml

        from tf_operator_tpu.api import constants
        from tf_operator_tpu.api.types import CleanPodPolicy, ReplicaType, RestartPolicy

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with open(os.path.join(root, "deploy", "crd.yaml")) as f:
            crd = yaml.safe_load(f)
        spec = crd["spec"]
        assert crd["metadata"]["name"] == constants.CRD_NAME
        assert spec["group"] == constants.GROUP_NAME
        assert spec["names"]["plural"] == constants.PLURAL
        version = spec["versions"][0]
        assert version["name"] == constants.VERSION
        assert version["subresources"] == {"status": {}}
        schema = version["schema"]["openAPIV3Schema"]["properties"]["spec"]
        assert (
            tuple(schema["properties"]["cleanPodPolicy"]["enum"])
            == CleanPodPolicy.CHOICES
        )
        replica_specs = schema["properties"]["replicaSpecs"]
        replica_props = replica_specs["properties"]
        assert set(replica_props) == set(ReplicaType.ALL)
        # Unknown role keys are rejected by CEL (additionalProperties is
        # forbidden beside properties in v1 structural schemas).
        cel = replica_specs["x-kubernetes-validations"][0]["rule"]
        assert all(rtype in cel for rtype in ReplicaType.ALL)
        worker = replica_props["Worker"]
        assert (
            tuple(worker["properties"]["restartPolicy"]["enum"]) == RestartPolicy.ALL
        )
        assert worker["required"] == ["template"]

    def test_operator_manifest_parses(self):
        import yaml

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with open(os.path.join(root, "deploy", "operator.yaml")) as f:
            docs = list(yaml.safe_load_all(f))
        kinds = [d["kind"] for d in docs]
        assert kinds == [
            "ServiceAccount",
            "ClusterRole",
            "ClusterRoleBinding",
            "Deployment",
        ]
        role = docs[1]
        groups = {g for rule in role["rules"] for g in rule["apiGroups"]}
        assert "tpuflow.org" in groups and "coordination.k8s.io" in groups


class TestOperatorCli:
    def test_backend_kube_flags_parse(self):
        from tf_operator_tpu.cli.operator import build_parser

        args = build_parser().parse_args(
            ["--backend", "kube", "--kubeconfig", "/tmp/kc", "--kube-context", "dev"]
        )
        assert args.backend == "kube"
        assert args.kubeconfig == "/tmp/kc"
        assert args.kube_context == "dev"

    def test_backend_kube_bad_config_exits_2(self, tmp_path, monkeypatch):
        from tf_operator_tpu.cli import operator as operator_cli

        monkeypatch.delenv("KUBERNETES_SERVICE_HOST", raising=False)
        rc = operator_cli.main(
            ["--backend", "kube", "--kubeconfig", str(tmp_path / "missing")]
        )
        assert rc == 2

    def test_backend_kube_master_conflict_exits_2(self, tmp_path):
        from tf_operator_tpu.cli import operator as operator_cli

        rc = operator_cli.main(
            ["--backend", "kube", "--master", "http://x", "--kubeconfig", "/nope"]
        )
        assert rc == 2


# ---------------------------------------------------------------------------
# The controller running over the kube adapter (full reconcile loop across
# a real HTTP boundary speaking the K8s wire protocol).
# ---------------------------------------------------------------------------

class TestControllerOverKube:
    def test_sync_creates_pods_and_status_through_kube_api(self):
        from tf_operator_tpu.controller.tpujob_controller import TPUJobController
        from tf_operator_tpu.utils import testutil

        stub = KubeApiStub()
        stub.start()
        client = KubeClusterClient(KubeConfig(server=stub.url))
        try:
            job = testutil.new_tpujob(name="kubejob", worker=2)
            client.create(objects.TPUJOBS, job.to_dict())
            tc = TPUJobController(client)
            tc.job_informer.sync_now()
            tc.pod_informer.sync_now()
            tc.service_informer.sync_now()
            tc.sync_job("default/kubejob")

            pods = client.list(objects.PODS, "default")
            assert len(pods) == 2
            services = client.list(objects.SERVICES, "default")
            assert len(services) == 2
            # Mark pods running through the kube API, resync, and verify the
            # Running condition lands via the status subresource.
            for p in pods:
                p["status"]["phase"] = objects.RUNNING
                client.update_status(objects.PODS, p)
            # Resync all informers so the creation expectations from sync 1
            # (pods AND services) are observed before the next sync.
            tc.pod_informer.sync_now()
            tc.service_informer.sync_now()
            tc.job_informer.sync_now()
            tc.sync_job("default/kubejob")
            stored = client.get(objects.TPUJOBS, "default", "kubejob")
            types = [c["type"] for c in stored["status"]["conditions"]]
            assert "Running" in types
        finally:
            stub.stop()


# ---------------------------------------------------------------------------
# Aggregating proxy: ApiServer + dashboard + /metrics over the kube backend
# (the in-cluster serving mode of deploy/operator.yaml)
# ---------------------------------------------------------------------------


def test_apiserver_proxies_over_kube_backend():
    """`--serve` with `--backend kube`: the framework apiserver (REST +
    dashboard + observability) rides KubeClusterClient, so a dashboard
    create lands in the real (stubbed) K8s apiserver and /metrics serves."""
    import urllib.request

    from tf_operator_tpu.dashboard.backend import mount_dashboard
    from tf_operator_tpu.runtime.apiserver import ApiServer
    from tf_operator_tpu.runtime.observability import mount_observability
    from tf_operator_tpu.utils import testutil

    stub = KubeApiStub()
    stub.start()
    client = KubeClusterClient(KubeConfig(server=stub.url))
    api = ApiServer(client, port=0)
    mount_observability(api)
    mount_dashboard(api, client)
    api.start()
    base = f"http://127.0.0.1:{api.port}"
    try:
        job = testutil.new_tpujob(name="proxyjob", worker=1).to_dict()
        req = urllib.request.Request(
            f"{base}/tpujobs/api/tpujob", method="POST",
            data=json.dumps(job).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=5) as resp:
            assert resp.status == 201
        # The write went THROUGH the proxy into the stubbed K8s apiserver.
        assert stub.cluster.get(objects.TPUJOBS, "default", "proxyjob")
        # And reads come back through the same path.
        with urllib.request.urlopen(
            f"{base}/tpujobs/api/tpujob/default/proxyjob", timeout=5
        ) as resp:
            detail = json.loads(resp.read())
        assert detail["tpujob"]["metadata"]["name"] == "proxyjob"
        # Deterministic metric registration: the controller module
        # registers the tpu_operator_* families at import time, which a
        # standalone run of this test would otherwise never trigger.
        from tf_operator_tpu.controller import tpujob_controller as tc_mod

        assert tc_mod.SYNC_SECONDS is not None  # families registered
        with urllib.request.urlopen(f"{base}/metrics", timeout=5) as resp:
            assert b"tpu_operator" in resp.read()
    finally:
        api.stop()
        stub.stop()


def test_full_job_lifecycle_over_kube_backend():
    """The operator E2E on the Kubernetes wire: a live TPUJobController
    backed by KubeClusterClient against the apiserver stub, with a fake
    kubelet advancing pods — the job must reach Succeeded via status-
    subresource writes, and CleanPodPolicy GC must run, all through K8s
    REST conventions."""
    import threading

    from tools.bench_control_plane import WatchKubelet

    from tf_operator_tpu.cli.genjob import synthetic_job
    from tf_operator_tpu.controller.jobcontroller import JobControllerConfig
    from tf_operator_tpu.controller.tpujob_controller import TPUJobController

    stub = KubeApiStub()
    stub.start()
    client = KubeClusterClient(KubeConfig(server=stub.url))
    tc = TPUJobController(
        client, JobControllerConfig(reconcile_period=0.2, informer_resync=0.5)
    )
    stop = threading.Event()
    threading.Thread(target=tc.run, args=(stop,), daemon=True).start()
    # the kubelet also talks to the cluster over the wire client — watch-
    # driven (it never lists), the same kubelet the scale bench uses
    kubelet = WatchKubelet(KubeClusterClient(KubeConfig(server=stub.url)), stop)
    kubelet.start()
    try:
        job = synthetic_job("wire", "default", 2, None, None)
        job["spec"]["cleanPodPolicy"] = "All"
        client.create(objects.TPUJOBS, job)

        deadline = time.monotonic() + 20
        conds = {}
        while time.monotonic() < deadline:
            stored = stub.cluster.get(objects.TPUJOBS, "default", "wire")
            conds = {
                c["type"]: c["status"]
                for c in stored.get("status", {}).get("conditions", [])
            }
            if conds.get("Succeeded") == "True":
                break
            time.sleep(0.2)
        assert conds.get("Succeeded") == "True", conds
        # status was written via the /status subresource path and replica
        # counters rolled up over the wire
        rs = stored["status"]["replicaStatuses"]["Worker"]
        assert rs["succeeded"] == 2, rs
        # CleanPodPolicy All: pods GC'd from the (stub) cluster
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if not stub.cluster.list(objects.PODS, "default"):
                break
            time.sleep(0.2)
        assert not stub.cluster.list(objects.PODS, "default")
    finally:
        stop.set()
        time.sleep(0.3)
        stub.stop()


# ---------------------------------------------------------------------------
# Optional real-cluster smoke (skipped unless pointed at a cluster)
# ---------------------------------------------------------------------------

@pytest.mark.slow
@pytest.mark.skipif(
    not os.environ.get("TPUFLOW_E2E_KUBECONFIG"),
    reason="set TPUFLOW_E2E_KUBECONFIG to a kubeconfig to run the "
    "real-apiserver smoke (no cluster in CI)",
)
def test_real_apiserver_smoke():
    """The contract cases a stub cannot fully vouch for — auth handshake,
    TLS, pagination against real etcd, RV semantics across compaction —
    exercised against an actual apiserver (kind/minikube/GKE) when one is
    provided. Creates and deletes a namespaced ConfigMap-scale object (a
    Pod) and round-trips list/watch."""
    cfg = load_kubeconfig(os.environ["TPUFLOW_E2E_KUBECONFIG"])
    client = KubeClusterClient(cfg, list_page_size=2)
    name = f"tpuflow-smoke-{os.getpid()}"
    p = pod(name)
    p["spec"] = {
        "containers": [{"name": "pause", "image": "registry.k8s.io/pause:3.9"}]
    }
    created = client.create(objects.PODS, p)
    try:
        assert objects.uid_of(created)
        # Paginated list path against real etcd.
        listed = client.list(objects.PODS, "default")
        assert any(objects.name_of(o) == name for o in listed)
        w = client.watch(objects.PODS, "default")
        try:
            # The watch pins its resourceVersion asynchronously; keep
            # patching (each patch is a fresh event) until one is delivered
            # instead of racing a fixed sleep against a remote apiserver.
            deadline = time.monotonic() + 30
            saw = False
            n = 0
            while time.monotonic() < deadline and not saw:
                client.patch_merge(
                    objects.PODS, "default", name,
                    {"metadata": {"labels": {"tpuflow-smoke": str(n)}}},
                )
                n += 1
                ev = w.next(timeout=1.0)
                saw = ev is not None and objects.name_of(ev.object) == name
            assert saw, "watch never delivered any patch event"
        finally:
            client.stop_watch(w)
    finally:
        client.delete(objects.PODS, "default", name)


class TestRestClientMetrics:
    def test_request_latency_and_watch_restarts_observed(self):
        """client-go restclient-metrics parity: API calls land in the
        request-latency histogram (by method/code) and a severed watch
        stream bumps the restart counter with its cause."""
        from tf_operator_tpu.runtime.client import NotFound
        from tf_operator_tpu.runtime.kubeclient import (
            REQUEST_SECONDS,
            WATCH_RESTARTS,
        )

        def post_ok_count() -> int:
            # Success codes are EXACT (200/201, matching client-go's
            # restclient metrics); accept either for create.
            return sum(REQUEST_SECONDS.snapshot(method="POST", code="200")) \
                + sum(REQUEST_SECONDS.snapshot(method="POST", code="201"))

        stub = KubeApiStub()
        stub.start()
        try:
            client = KubeClusterClient(
                KubeConfig(server=stub.url), watch_timeout_seconds=30.0
            )
            before = post_ok_count()
            client.create(objects.PODS, pod("metric-pod"))
            client.get(objects.PODS, "default", "metric-pod")
            assert post_ok_count() > before, "POST not observed"
            # A failing request records its exact code.
            nf_before = REQUEST_SECONDS.snapshot(method="GET", code="404")
            with pytest.raises(NotFound):
                client.get(objects.PODS, "default", "no-such")
            assert sum(
                REQUEST_SECONDS.snapshot(method="GET", code="404")
            ) > sum(nf_before)

            # Severed stream -> eof restart counted for this kind.
            eof_before = WATCH_RESTARTS.value(
                kind=objects.PODS, reason="eof"
            )
            w = client.watch(objects.PODS, "default")
            # Keep creating until an event arrives: the watch thread's
            # initial LIST races the first create (same pattern as the
            # bookmark test above).
            e = None
            deadline = time.monotonic() + 10.0
            i = 0
            while time.monotonic() < deadline and e is None:
                client.create(objects.PODS, pod(f"metric-pod-{i}"))
                i += 1
                e = w.next(timeout=0.5)
            assert e is not None, "watch never delivered"
            assert stub.kill_watches() >= 1
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline and (
                WATCH_RESTARTS.value(kind=objects.PODS, reason="eof")
                <= eof_before
            ):
                time.sleep(0.1)
            assert WATCH_RESTARTS.value(
                kind=objects.PODS, reason="eof"
            ) > eof_before
            client.stop_watch(w)
        finally:
            stub.stop()
