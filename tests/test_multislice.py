"""Multislice (MEGASCALE) E2E: a num_slices=2 job through the local stack.

Verifies the DCN-multislice contract end to end (SURVEY.md §2.9 "keep DNS
rendezvous for inter-slice DCN"): every replica of a 2-slice v5e-16 job
echoes its injected topology env via GET /topology, and the env partitions
the replica set per slice — in-slice worker ids and coordinator, shared
MEGASCALE coordinator on slice 0. Plus the training-side analog: a dcn mesh
axis over the virtual CPU mesh whose gradient all-reduce spans slices.
"""

import threading
import time

import jax.numpy as jnp
import pytest

from tf_operator_tpu.api import constants
from tf_operator_tpu.controller.jobcontroller import JobControllerConfig
from tf_operator_tpu.controller.tpujob_controller import TPUJobController
from tf_operator_tpu.runtime import objects
from tf_operator_tpu.runtime.executor import LocalProcessExecutor
from tf_operator_tpu.runtime.gc import OwnerGarbageCollector
from tf_operator_tpu.runtime.memcluster import InMemoryCluster
from tf_operator_tpu.topology import slices as topo_slices

from test_e2e_local import SERVER_CMD, http_get, job_condition, wait_for

ACCELERATOR = "v5e-16"  # 4 hosts per slice
NUM_SLICES = 2


@pytest.fixture()
def stack():
    client = InMemoryCluster()
    tc = TPUJobController(
        client,
        JobControllerConfig(reconcile_period=0.2, informer_resync=0.5, threadiness=2),
    )
    executor = LocalProcessExecutor(client)
    collector = OwnerGarbageCollector(client)
    stop = threading.Event()
    threading.Thread(target=tc.run, args=(stop,), daemon=True).start()
    executor.start(stop)
    collector.start(stop)
    time.sleep(0.3)
    yield client, executor
    stop.set()
    time.sleep(0.3)


def submit_multislice_job(client, name="ms"):
    return client.create(
        objects.TPUJOBS,
        {
            "apiVersion": constants.API_VERSION,
            "kind": constants.KIND,
            "metadata": {"name": name, "namespace": "default"},
            "spec": {
                "replicaSpecs": {
                    "Worker": {
                        "tpu": {
                            "acceleratorType": ACCELERATOR,
                            "numSlices": NUM_SLICES,
                        },
                        "template": {
                            "spec": {
                                "containers": [
                                    {
                                        "name": constants.DEFAULT_CONTAINER_NAME,
                                        "image": "local",
                                        "command": SERVER_CMD,
                                    }
                                ]
                            }
                        },
                    }
                }
            },
        },
    )


@pytest.mark.slow
def test_two_slice_job_partitions_topology_env(stack):
    client, executor = stack
    topo = topo_slices.resolve(ACCELERATOR)
    hosts_per_slice = topo.num_hosts
    total = hosts_per_slice * NUM_SLICES

    submit_multislice_job(client)
    wait_for(job_condition(client, "ms", "Running"), timeout=90,
             desc="ms job Running")
    pods = wait_for(
        lambda: (lambda ps: ps if len(ps) == total else None)(
            client.list(objects.PODS, "default")
        ),
        desc=f"{total} replica pods",
    )
    assert len(pods) == total

    port = constants.DEFAULT_PORT
    seen_megascale_coords = set()
    for i in range(total):
        topo_env = http_get(executor, f"ms-worker-{i}", "/topology")
        slice_id, worker_id = divmod(i, hosts_per_slice)
        base = slice_id * hosts_per_slice
        slice_hosts = [f"ms-worker-{base + j}" for j in range(hosts_per_slice)]

        # In-slice partition: this slice's hosts only, in index order.
        assert topo_env[constants.ENV_TPU_WORKER_HOSTNAMES] == ",".join(slice_hosts)
        assert topo_env[constants.ENV_TPU_WORKER_ID] == str(worker_id)
        assert topo_env[constants.ENV_NUM_PROCESSES] == str(hosts_per_slice)
        # Per-slice coordinator = worker 0 *of that slice*. The local
        # executor rewrites "{pod}:{port}" contracts to the replica's real
        # reachable address, so resolve the expectation the same way.
        ip0, port0 = executor.resolve(slice_hosts[0])
        assert topo_env[constants.ENV_COORDINATOR_ADDRESS] == f"{ip0}:{port0}"
        assert topo_env[constants.ENV_TPU_ACCELERATOR_TYPE] == ACCELERATOR

        # Cross-slice MEGASCALE wiring: slice count, own slice id, and one
        # shared DCN coordinator (slice 0's worker 0) for every replica.
        assert topo_env["MEGASCALE_NUM_SLICES"] == str(NUM_SLICES)
        assert topo_env["MEGASCALE_SLICE_ID"] == str(slice_id)
        seen_megascale_coords.add(topo_env["MEGASCALE_COORDINATOR_ADDRESS"])
    # The DCN rendezvous rides its own per-pod port (distinct from the
    # in-slice coordinator port — they share a pod on slice 0's worker 0).
    dcn_ip, dcn_port = executor.resolve_dcn("ms-worker-0")
    assert seen_megascale_coords == {f"{dcn_ip}:{dcn_port}"}
    assert (dcn_ip, dcn_port) != executor.resolve("ms-worker-0")

    # Tear down: terminate every replica cleanly; the job must reach
    # Succeeded only when all slices have finished.
    for i in range(total):
        http_get(executor, f"ms-worker-{i}", "/exit?exitCode=0")
    wait_for(job_condition(client, "ms", "Succeeded"), timeout=90,
             desc="ms job Succeeded")


@pytest.mark.slow
def test_two_process_group_rendezvous_trains_across_slices(stack):
    """The MEGASCALE contract drives REAL process groups, not just env
    strings: a 2-slice v4-8 job (2 hosts per slice) launches 4 processes;
    each slice bootstraps its own jax.distributed coordinator from the
    in-slice contract, and the slices synchronize params through the DCN
    channel at MEGASCALE_COORDINATOR_ADDRESS every step. The workload's
    ground truth differs per slice, so reaching the GLOBAL optimum (its
    exit-0 condition) is only possible if the cross-group reduction moved
    real data — two coordinators + a DCN leg, end to end."""
    import os as _os
    import sys as _sys

    client, executor = stack
    examples = _os.path.join(
        _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))),
        "examples",
    )
    client.create(
        objects.TPUJOBS,
        {
            "apiVersion": constants.API_VERSION,
            "kind": constants.KIND,
            "metadata": {"name": "ms2", "namespace": "default"},
            "spec": {
                "replicaSpecs": {
                    "Worker": {
                        "tpu": {"acceleratorType": "v4-8", "numSlices": 2},
                        "template": {
                            "spec": {
                                "containers": [
                                    {
                                        "name": constants.DEFAULT_CONTAINER_NAME,
                                        "image": "local",
                                        "command": [
                                            _sys.executable,
                                            _os.path.join(
                                                examples, "dist_multislice.py"
                                            ),
                                            "--steps", "40",
                                        ],
                                        "env": [
                                            # CPU rendezvous: disable the
                                            # environment's TPU plugin, one
                                            # device per process so the
                                            # in-slice dp axis spans the two
                                            # processes of each group.
                                            {"name": "JAX_PLATFORMS",
                                             "value": "cpu"},
                                            {"name": "PALLAS_AXON_POOL_IPS",
                                             "value": ""},
                                            {"name": "XLA_FLAGS", "value":
                                             "--xla_force_host_platform_device_count=1"},
                                        ],
                                    }
                                ]
                            }
                        },
                    }
                }
            },
        },
    )
    wait_for(job_condition(client, "ms2", "Succeeded"), timeout=600,
             desc="ms2 multislice job Succeeded")
    # Every replica reported the global optimum reached + cross-slice
    # agreement (the workload exits nonzero otherwise); spot-check logs.
    from tf_operator_tpu.runtime import podlogs

    ok = 0
    for i in range(4):
        log = podlogs.read_log("default", f"ms2-worker-{i}") or ""
        if "dist_multislice: OK" in log:
            ok += 1
    assert ok == 4, f"only {ok}/4 replicas reported OK"


@pytest.mark.slow
@pytest.mark.e2e_smoke
def test_two_process_fsdp_state_sharded_across_slices(stack):
    """dcn x fsdp as a REAL multi-process job: 2 slices x 2 hosts; each
    slice's params + momentum are sharded over its own process group's
    devices (ZeRO-in-slice), gathered only for the per-step DCN sync.
    Exit-0 requires convergence to the GLOBAL optimum AND the workload's
    own check that both state tensors carry an in-slice-sharded spec."""
    import os as _os
    import sys as _sys

    client, executor = stack
    examples = _os.path.join(
        _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))),
        "examples",
    )
    client.create(
        objects.TPUJOBS,
        {
            "apiVersion": constants.API_VERSION,
            "kind": constants.KIND,
            "metadata": {"name": "ms3", "namespace": "default"},
            "spec": {
                "replicaSpecs": {
                    "Worker": {
                        "tpu": {"acceleratorType": "v4-8", "numSlices": 2},
                        "template": {
                            "spec": {
                                "containers": [
                                    {
                                        "name": constants.DEFAULT_CONTAINER_NAME,
                                        "image": "local",
                                        "command": [
                                            _sys.executable,
                                            _os.path.join(
                                                examples, "dist_multislice.py"
                                            ),
                                            "--steps", "40", "--fsdp",
                                        ],
                                        "env": [
                                            {"name": "JAX_PLATFORMS",
                                             "value": "cpu"},
                                            {"name": "PALLAS_AXON_POOL_IPS",
                                             "value": ""},
                                            # 2 devices per process: the
                                            # in-slice axis is 4 wide (2
                                            # procs x 2), so dim 8 shards
                                            # 2 elements per device.
                                            {"name": "XLA_FLAGS", "value":
                                             "--xla_force_host_platform_device_count=2"},
                                        ],
                                    }
                                ]
                            }
                        },
                    }
                }
            },
        },
    )
    wait_for(job_condition(client, "ms3", "Succeeded"), timeout=600,
             desc="ms3 fsdp multislice job Succeeded")
    from tf_operator_tpu.runtime import podlogs

    ok = sharded = 0
    for i in range(4):
        log = podlogs.read_log("default", f"ms3-worker-{i}") or ""
        ok += "dist_multislice: OK" in log
        sharded += "fsdp state sharded over 4 in-slice devices" in log
    assert ok == 4, f"only {ok}/4 replicas reported OK"
    assert sharded == 4, f"only {sharded}/4 replicas confirmed sharding"


def test_dcn_mesh_trains_across_slices():
    """Training-side multislice analog on the virtual CPU mesh: a dcn x dp
    mesh (2 slices x 4 chips), batch sharded over both data axes; the
    gradient reduction must span the dcn axis (cross-slice traffic)."""
    import jax

    from tf_operator_tpu.models.mnist import MnistCNN
    from tf_operator_tpu.parallel.mesh import multislice_mesh
    from tf_operator_tpu.parallel.sharding import replicate, shard_batch
    from tf_operator_tpu.train.steps import (
        TrainState,
        make_classifier_train_step,
        sgd_momentum,
    )

    mesh = multislice_mesh(2, {"dp": 4})
    assert tuple(mesh.axis_names)[0] == "dcn"  # outermost: ICI inside slices
    assert mesh.shape == {"dcn": 2, "dp": 4}

    model = MnistCNN(dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 28, 28, 1))
    y = jax.random.randint(jax.random.PRNGKey(1), (16,), 0, 10)
    params = model.init(jax.random.PRNGKey(2), x, train=True)["params"]
    tx = sgd_momentum(0.01)
    state = replicate(mesh, TrainState.create(params, tx))
    step = make_classifier_train_step(
        model, tx, mesh, has_batch_stats=False, data_axis=("dcn", "dp"),
        donate=False,
    )
    batch = shard_batch(mesh, {"image": x, "label": y}, axis=("dcn", "dp"))

    # The gradient all-reduce must include the dcn dimension: with 8 devices
    # in 2 slices the reduction group covers all devices, not one slice
    # ([1,8]<=[8] is the iota form of one group of all 8).
    txt = step.lower(state, batch).compile().as_text()
    assert "all-reduce" in txt
    assert (
        "replica_groups=[1,8]<=[8]" in txt
        or "replica_groups={{0,1,2,3,4,5,6,7}}" in txt
    )

    state, metrics = step(state, batch)
    assert jnp.isfinite(metrics["loss"])


def test_dcn_fsdp_shards_state_in_slice_only():
    """dcn x fsdp — the deployment shape BASELINE's multislice config
    implies: params + optimizer moments sharded over the IN-SLICE fsdp
    axis, replicated across slices; batch over (dcn, fsdp). The compiled
    step must keep the fsdp all-gather within slices (ICI groups) while
    the gradient reduction spans slices (DCN) — pinned on the HLO replica
    groups."""
    import re

    import jax

    from tf_operator_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
    )
    from tf_operator_tpu.parallel.mesh import multislice_mesh
    from tf_operator_tpu.parallel.sharding import (
        fsdp_sharding_tree,
        shard_batch,
        shard_params_fsdp,
    )
    from tf_operator_tpu.train.steps import (
        TrainState,
        adamw,
        make_lm_train_step,
    )

    mesh = multislice_mesh(2, {"fsdp": 4})
    cfg = TransformerConfig(
        vocab_size=64, d_model=64, n_heads=4, n_layers=2, d_ff=128,
        max_seq_len=32, dtype=jnp.float32, mesh=None,
    )
    model = Transformer(cfg)
    toks = jnp.zeros((16, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(8), toks)["params"]
    tree = fsdp_sharding_tree(mesh, params, min_size=64)
    params = shard_params_fsdp(mesh, params, min_size=64)
    tx = adamw(1e-3)
    state = TrainState.create(params, tx)
    step = make_lm_train_step(
        model, tx, mesh, data_axis=("dcn", "fsdp"), seq_axis=None,
        param_shardings=tree, xent_chunk=8, donate=False,
    )
    batch = shard_batch(
        mesh, {"tokens": toks, "targets": toks}, axis=("dcn", "fsdp")
    )

    txt = step.lower(state, batch).compile().as_text()
    groups = set(re.findall(r"replica_groups=\[[^\]]*\]<=\[[0-9,]*\]", txt))
    # fsdp param all-gather: 2 groups of 4 consecutive devices = within
    # each slice, riding ICI.
    assert "all-gather" in txt
    assert "replica_groups=[2,4]<=[8]" in groups, groups
    # Gradient reduction spans slices: either the global all-reduce or
    # the dcn-only pairs ([4,2]<=[2,4] = 4 cross-slice groups of 2).
    assert (
        "replica_groups=[1,8]<=[8]" in groups
        or "replica_groups=[4,2]<=[2,4]" in groups
    ), groups

    state, metrics = step(state, batch)
    assert jnp.isfinite(metrics["loss"])
    # Optimizer moments for large kernels are genuinely fsdp-sharded
    # (the step's outputs may surface as NamedSharding or GSPMD — the
    # spec string is the stable signal), never sharded over dcn.
    specs = [
        str(getattr(leaf.sharding, "spec", ""))
        for leaf in jax.tree.leaves(state.opt_state)
        if hasattr(leaf, "sharding") and leaf.size >= 64
    ]
    assert any("fsdp" in s for s in specs), (
        "no optimizer leaf carries an fsdp-sharded spec", specs[:5])
    assert not any("dcn" in s for s in specs), (
        "optimizer state must not shard over the DCN axis", specs[:5])
