"""The hardware-window tooling (tools/window_*.py) — the capture path for
every hardware number this round, so its plumbing is suite-tested: stage
command construction, useful-line gating (what marks a stage done), and
report rendering from artifacts."""

import json
import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from tools import window_autorun as wa  # noqa: E402


def test_stage_argv_construction():
    labels = [label for label, _, _ in wa.STAGES]
    assert labels[0] == "roofline"  # chip-state first
    assert "bench_full" in labels and "synthetic" in labels
    for label, env_over, budget in wa.STAGES:
        argv, env = wa.stage_argv(label, dict(env_over) if env_over else None)
        assert argv[0] == sys.executable
        assert os.path.exists(argv[1]), argv
        if env_over and "PROBE" in env_over:
            assert env["PROBE"] == env_over["PROBE"]
            assert env["BENCH_WATCHDOG_S"] == "0"
        elif env_over and "BENCH" in env_over:
            assert argv[2:] == ["--section", env_over["BENCH"]]
        else:  # full bench keeps its own watchdog + section isolation
            assert "BENCH_WATCHDOG_S" not in env
        assert budget > 0
    # The A/B legs pin the attention knob on BOTH sides.
    flash_env = dict(next(e for l, e, _ in wa.STAGES if l == "lm_ab_flash"))
    xla_env = dict(next(e for l, e, _ in wa.STAGES if l == "lm_ab_xla"))
    assert flash_env["TPU_OPERATOR_ATTN"] == ""
    assert xla_env["TPU_OPERATOR_ATTN"] == "xla"


def test_useful_lines_gating(tmp_path):
    """What counts as 'stage produced data': error rows and the CPU-only
    submit-latency line must NOT mark a hardware stage done (the
    BENCH_r03 rc=3 shape)."""
    p = tmp_path / "out.jsonl"
    p.write_text(
        json.dumps({"metric": "tpujob_submit_to_all_running_median_ms",
                    "value": 90}) + "\n"
        + "bench: some stderr-ish line\n"
        + json.dumps({"probe": "lmsweep", "size": "840M",
                      "error": "RESOURCE_EXHAUSTED"}) + "\n"
    )
    assert wa._useful_lines(str(p), "bench_full") == 0
    with open(p, "a") as f:
        f.write(json.dumps({"metric": "resnet50_train_images_per_sec",
                            "value": 2500}) + "\n")
    assert wa._useful_lines(str(p), "bench_full") == 1
    assert wa._useful_lines(str(tmp_path / "missing.jsonl"), "x") == 0


def test_report_renders_from_artifacts(tmp_path):
    """window_report renders every section from a synthetic window dir —
    including the degenerate cases (error rows, missing stages)."""
    d = tmp_path / "win"
    d.mkdir()
    (d / "roofline.jsonl").write_text(json.dumps({
        "probe": "roofline", "dispatch_roundtrip_ms": 0.06,
        "matmul_chain_tflops": 111.0, "copy_gbps": 111.0,
        "matmul_8192_tflops": 86.0,
    }) + "\n")
    (d / "synthetic.jsonl").write_text(json.dumps({
        "probe": "synthetic", "images_per_sec": 2500.0,
        "images_per_sec_b2x": 2800.0,
    }) + "\n")
    (d / "bench_full.jsonl").write_text(
        json.dumps({"metric": "resnet50_train_images_per_sec_bf16_b256_1chip",
                    "value": 2400.0, "mfu": 0.30,
                    "flops_source": "analytic"}) + "\n"
        + json.dumps({"metric": "lm_decode_gen_tokens_per_sec_int8_b8_1chip",
                      "value": 900.0, "hbm_gbps": 60.0}) + "\n"
    )
    (d / "decodesweep.jsonl").write_text(
        json.dumps({"probe": "decodesweep", "weights": "bf16", "batch": 8,
                    "gen_tokens_per_sec": 500.0, "hbm_gbps": 47.0}) + "\n"
        + json.dumps({"probe": "decodesweep", "weights": "int8", "batch": 8,
                      "error": "boom"}) + "\n"
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", "window_report.py"),
         str(d)],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    out = proc.stdout
    assert "111.0 TFLOP/s" in out or "111.0" in out
    assert "2500" in out and "2400" in out
    # Measured-ceiling re-denomination: 0.30 spec MFU * 197/111 = 53%.
    assert "53." in out
    # Error row doesn't crash the report, and no speedup line is printed.
    assert "boom" not in out
    assert "int8 speedup" not in out


def test_foreign_bench_detector_ignores_own_children(tmp_path):
    """The yield-to-driver scan is structural (argv[1] is the script
    path): text mentions of bench.py in other processes' cmdlines (e.g.
    the driver wrapper's prompt) must not trigger it."""
    script = tmp_path / "not_a_bench.py"
    script.write_text("import time; time.sleep(5)\n")
    p = subprocess.Popen(
        [sys.executable, str(script), "this mentions bench.py in an arg"]
    )
    try:
        assert wa._foreign_bench_running() is False
    finally:
        p.terminate()
        p.wait()
