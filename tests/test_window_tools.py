"""The hardware-window tooling (tools/window_*.py) — the capture path for
every hardware number this round, so its plumbing is suite-tested: stage
command construction, useful-line gating (what marks a stage done), and
report rendering from artifacts."""

import json
import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from tools import window_autorun as wa  # noqa: E402


def test_stage_argv_construction():
    labels = [label for label, _, _ in wa.STAGES]
    assert labels[0] == "roofline"  # chip-state first
    assert "bench_full" in labels and "synthetic" in labels
    for label, env_over, budget in wa.STAGES:
        argv, env = wa.stage_argv(label, dict(env_over) if env_over else None)
        assert argv[0] == sys.executable
        assert os.path.exists(argv[1]), argv
        if env_over and "PROBE" in env_over:
            assert env["PROBE"] == env_over["PROBE"]
            assert env["BENCH_WATCHDOG_S"] == "0"
        elif env_over and "BENCH" in env_over:
            assert argv[2:] == ["--section", env_over["BENCH"]]
        else:  # full bench keeps its own watchdog + section isolation
            assert "BENCH_WATCHDOG_S" not in env
        assert budget > 0
    # The A/B legs pin the attention knob on BOTH sides.
    flash_env = dict(next(e for l, e, _ in wa.STAGES if l == "lm_ab_flash"))
    xla_env = dict(next(e for l, e, _ in wa.STAGES if l == "lm_ab_xla"))
    assert flash_env["TPU_OPERATOR_ATTN"] == ""
    assert xla_env["TPU_OPERATOR_ATTN"] == "xla"


def test_useful_lines_gating(tmp_path):
    """What counts as 'stage produced data': error rows and the CPU-only
    submit-latency line must NOT mark a hardware stage done (the
    BENCH_r03 rc=3 shape)."""
    p = tmp_path / "out.jsonl"
    p.write_text(
        json.dumps({"metric": "tpujob_submit_to_all_running_median_ms",
                    "value": 90}) + "\n"
        + "bench: some stderr-ish line\n"
        + json.dumps({"probe": "lmsweep", "size": "840M",
                      "error": "RESOURCE_EXHAUSTED"}) + "\n"
    )
    assert wa._useful_lines(str(p), "bench_full") == 0
    with open(p, "a") as f:
        f.write(json.dumps({"metric": "resnet50_train_images_per_sec",
                            "value": 2500}) + "\n")
    assert wa._useful_lines(str(p), "bench_full") == 1
    assert wa._useful_lines(str(tmp_path / "missing.jsonl"), "x") == 0


def test_report_renders_from_artifacts(tmp_path):
    """window_report renders every section from a synthetic window dir —
    including the degenerate cases (error rows, missing stages)."""
    d = tmp_path / "win"
    d.mkdir()
    (d / "roofline.jsonl").write_text(json.dumps({
        "probe": "roofline", "dispatch_roundtrip_ms": 0.06,
        "matmul_chain_tflops": 111.0, "copy_gbps": 111.0,
        "matmul_8192_tflops": 86.0,
    }) + "\n")
    (d / "synthetic.jsonl").write_text(json.dumps({
        "probe": "synthetic", "images_per_sec": 2500.0,
        "images_per_sec_b2x": 2800.0,
    }) + "\n")
    (d / "bench_full.jsonl").write_text(
        json.dumps({"metric": "resnet50_train_images_per_sec_bf16_b256_1chip",
                    "value": 2400.0, "mfu": 0.30,
                    "flops_source": "analytic"}) + "\n"
        + json.dumps({"metric": "lm_decode_gen_tokens_per_sec_int8_b8_1chip",
                      "value": 900.0, "hbm_gbps": 60.0}) + "\n"
    )
    (d / "decodesweep.jsonl").write_text(
        json.dumps({"probe": "decodesweep", "weights": "bf16", "batch": 8,
                    "gen_tokens_per_sec": 500.0, "hbm_gbps": 47.0}) + "\n"
        + json.dumps({"probe": "decodesweep", "weights": "int8", "batch": 8,
                      "error": "boom"}) + "\n"
    )
    # r05-added stages: the chained-copy roofline re-run, the dispatch
    # Q-block arbitration, the resident ResNet mode, spec decoding.
    (d / "roofline2.jsonl").write_text(json.dumps({
        "probe": "roofline", "dispatch_roundtrip_ms": 0.05,
        "matmul_chain_tflops": 111.0, "copy_gbps": 111.0,
        "chain_copy_gbps": 400.0,
    }) + "\n")
    (d / "qblock.jsonl").write_text(json.dumps({
        "probe": "qblock", "auto_pair": [1024, 256],
        "dispatch_auto_tflops": 13.8, "direct_bq1024_tflops": 14.0,
        "direct_bq512_tflops": 11.0,
    }) + "\n")
    (d / "resnet_resident.jsonl").write_text(json.dumps({
        "metric": "resnet50_train_images_per_sec_bf16_b256_resident_1chip",
        "value": 2450.0, "mfu": 0.28,
    }) + "\n")
    (d / "specdecode.jsonl").write_text(json.dumps({
        "probe": "specdecode", "k": 4,
        "tokens_per_sec_plain": 1000.0,
        "tokens_per_sec_spec_self": 800.0,
        "tokens_per_sec_spec_cold": 400.0,
        "tokens_per_round_self": 5.0, "tokens_per_round_cold": 1.1,
    }) + "\n")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", "window_report.py"),
         str(d)],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    out = proc.stdout
    assert "111.0 TFLOP/s" in out or "111.0" in out
    assert "2500" in out and "2400" in out
    # Measured-ceiling re-denomination: 0.30 spec MFU * 197/111 = 53%.
    assert "53." in out
    # Error row doesn't crash the report, and no speedup line is printed.
    assert "boom" not in out
    assert "int8 speedup" not in out
    # roofline2's chained copy becomes the bandwidth yardstick: the
    # 47 GB/s decode row re-denominates to 47/400 = 11.8%.
    assert "scan-chained" in out and "400.0" in out
    assert "11.8%" in out
    # qblock, resident, and specdecode sections render.
    assert "dispatch_auto=13.8" in out
    assert "resident mode" in out and "2450.0" in out
    assert "spec_self (k=4)" in out and "0.80x" in out


def test_report_attribution_math_round3_shaped(tmp_path):
    """Dry-run of the report against a stamp dir shaped like the ROUND-3
    measured data (docs/bench_r03_measured.jsonl) plus plausible probe
    lines — pins the exact joins a first real window will exercise: the
    synthetic-vs-bench ResNet split verdict, ceilings re-denomination,
    the LM A/B fallback warning, the lmsweep table, and the int8 speedup
    line (VERDICT r4 item 7)."""
    d = tmp_path / "20260801T000000"
    d.mkdir()
    (d / "roofline.jsonl").write_text(json.dumps({
        "probe": "roofline", "dispatch_roundtrip_ms": 0.056,
        "matmul_chain_tflops": 111.0, "copy_gbps": 111.0,
    }) + "\n")
    # Synthetic (device-resident) far above the r03 end-to-end 59.9:
    # the split must attribute the collapse to input/transfer.
    (d / "synthetic.jsonl").write_text(json.dumps({
        "probe": "synthetic", "images_per_sec": 2500.0,
        "images_per_sec_b2x": 2900.0,
    }) + "\n")
    (d / "bench_full.jsonl").write_text("\n".join(json.dumps(m) for m in [
        {"metric": "resnet50_train_images_per_sec_bf16_b256_1chip",
         "value": 59.9, "mfu": 0.10, "flops_source": "analytic"},
        {"metric": "flash_attention_fwd_bwd_tflops_bf16_seq8192_1chip",
         "value": 0.1},
        {"metric": "lm_decode_gen_tokens_per_sec_bf16_b8_1chip",
         "value": 470.4, "hbm_gbps": 47.44},
    ]) + "\n")
    (d / "lm_ab_flash.jsonl").write_text(json.dumps(
        {"metric": "transformer_lm_tokens_per_sec_bf16_seq8192_1chip",
         "value": 4544.2}) + "\n")
    (d / "lm_ab_xla.jsonl").write_text(json.dumps(
        {"metric": "transformer_lm_tokens_per_sec_bf16_seq8192_1chip",
         "value": 9000.0}) + "\n")
    (d / "lmsweep.jsonl").write_text("\n".join(json.dumps(m) for m in [
        {"probe": "lmsweep", "size": "176M", "params_millions": 176.3,
         "tokens_per_sec": 4544.2, "mfu_spec": 0.034},
        {"probe": "lmsweep", "size": "840M",
         "error": "RESOURCE_EXHAUSTED"},
    ]) + "\n")
    (d / "decodesweep.jsonl").write_text("\n".join(json.dumps(m) for m in [
        {"probe": "decodesweep", "weights": "bf16", "batch": 8,
         "gen_tokens_per_sec": 470.4, "hbm_gbps": 47.4},
        {"probe": "decodesweep", "weights": "int8", "batch": 8,
         "gen_tokens_per_sec": 846.7, "hbm_gbps": 42.7},
    ]) + "\n")
    (d / "decodelong.jsonl").write_text("\n".join(json.dumps(m) for m in [
        {"probe": "decodelong", "batch": 8, "context": 4096,
         "cache": "bf16", "gen_tokens_per_sec": 100.0,
         "mean_tokens_per_sec": 95.0, "hbm_gbps": 80.0,
         "kv_read_fraction": 0.758},
        {"probe": "decodelong", "batch": 8, "context": 4096,
         "cache": "kv8", "gen_tokens_per_sec": 160.0,
         "mean_tokens_per_sec": 150.0, "hbm_gbps": 70.0,
         "kv_read_fraction": 0.611},
    ]) + "\n")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "tools", "window_report.py"),
         str(d)],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    out = proc.stdout
    # ResNet split: 59.9/2500 = 0.02 -> the input/transfer verdict.
    assert "0.02" in out and "input/transfer owns the gap" in out
    # Re-denomination: 0.10 spec MFU * 197/111 = 17.7% of measured.
    assert "17.7% of the measured" in out
    # LM A/B: flash/xla = 0.50 -> the dispatch-should-fall-back warning.
    assert "0.50x" in out and "DISPATCH SHOULD FALL" in out
    # lmsweep: 3.4% spec -> 6.0% measured; the OOM row renders as error.
    assert "6.0%" in out and "RESOURCE_EXHAUSTED"[:20] in out
    # Decode: int8 846.7/470.4 = 1.80x speedup line; copy-roofline pcts
    # (47.4/111 = 42.7%).
    assert "1.80x" in out
    assert "42.7" in out
    # Long-context cache A/B: 160/100 = 1.60x kv8 speedup + the kv read
    # fraction column.
    assert "1.60x" in out and "cache-read halving pays off" in out
    assert "75.8%" in out


def test_prior_round_submit_median_picks_newest(tmp_path):
    """The vs_prior_round drift check reads the newest BENCH_r*.json,
    whether the submit line is the driver's `parsed` field or buried in
    the `tail` string."""
    import bench

    (tmp_path / "BENCH_r03.json").write_text(json.dumps({
        "rc": 3,
        "tail": json.dumps({
            "metric": "tpujob_submit_to_all_running_median_ms",
            "value": 102.1}) + "\nbench: stderr noise\n",
    }))
    (tmp_path / "BENCH_r04.json").write_text(json.dumps({
        "rc": 3,
        "parsed": {"metric": "tpujob_submit_to_all_running_median_ms",
                   "value": 139.5},
    }))
    assert bench._prior_round_submit_median(str(tmp_path)) == 139.5
    # No artifacts at all -> None (first round): no crash, no warning.
    assert bench._prior_round_submit_median(str(tmp_path / "empty")) is None


def test_window_fallback_emits_tagged_lines(tmp_path, capsys):
    """Tunnel-down fold-in: metric lines are re-emitted tagged with
    source/captured_at; error rows, non-metric probe rows, and the stale
    submit line are dropped; within a stamp later stages win the dedupe;
    a PARTIAL newest capture must not shadow metrics only an older,
    fuller capture holds (each line keeps its own stamp)."""
    import bench

    old = tmp_path / "docs" / "window_r04" / "20260730T010101"
    new = tmp_path / "docs" / "window_r05" / "20260801T020202"
    old.mkdir(parents=True)
    new.mkdir(parents=True)
    (old / "bench_full.jsonl").write_text(
        json.dumps({"metric": "resnet50_train_images_per_sec_bf16_b256_1chip",
                    "value": 1000.0}) + "\n"
        + json.dumps({"metric": "flash_attention_fwd_bwd_tflops_bf16_seq8192_1chip",
                      "value": 40.0}) + "\n")
    (new / "synthetic.jsonl").write_text(
        json.dumps({"probe": "synthetic", "images_per_sec": 2500.0}) + "\n"
        + json.dumps({"metric": "resnet50_train_images_per_sec_bf16_b256_1chip",
                      "value": 2400.0, "mfu": 0.3}) + "\n")
    (new / "bench_full.jsonl").write_text(
        json.dumps({"metric": "tpujob_submit_to_all_running_median_ms",
                    "value": 90.0}) + "\n"
        + json.dumps({"metric": "resnet50_train_images_per_sec_bf16_b256_1chip",
                      "value": 2450.0, "mfu": 0.31}) + "\n"
        + json.dumps({"metric": "lm_decode_gen_tokens_per_sec_bf16_b8_1chip",
                      "error": "tunnel died"}) + "\n")
    bench._emit_window_fallback(str(tmp_path))
    lines = {l["metric"]: l for l in (
        json.loads(s) for s in capsys.readouterr().out.splitlines()
        if s.startswith("{")
    )}
    assert len(lines) == 2  # resnet (new) + flash (filled from old)
    resnet = lines["resnet50_train_images_per_sec_bf16_b256_1chip"]
    assert resnet["value"] == 2450.0  # bench_full beats synthetic in-stamp
    assert resnet["source"] == "window_autorun"
    assert resnet["captured_at"] == "20260801T020202"
    assert resnet["window_stage"] == "bench_full"
    flash = lines["flash_attention_fwd_bwd_tflops_bf16_seq8192_1chip"]
    assert flash["value"] == 40.0  # older stamp fills the gap...
    assert flash["captured_at"] == "20260730T010101"  # ...with its stamp


def test_window_fallback_legacy_when_no_captures(tmp_path, capsys):
    """With no window_r* captures the fold-in falls back to the round-3
    measured lines, tagged as such — a tunnel-down driver artifact always
    carries the latest real hardware numbers."""
    import bench

    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "bench_r03_measured.jsonl").write_text(
        json.dumps({"metric": "resnet50_train_images_per_sec_bf16_b256_1chip",
                    "value": 59.9}) + "\n"
        + json.dumps({"metric": "tpujob_submit_to_all_running_median_ms",
                      "value": 86.9}) + "\n")
    bench._emit_window_fallback(str(tmp_path))
    lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()
             if l.startswith("{")]
    assert [l["value"] for l in lines] == [59.9]
    assert lines[0]["source"] == "builder_round3_window"
    assert lines[0]["captured_at"]  # mtime-derived stamp present
    # Nothing at all -> silent no-op.
    bench._emit_window_fallback(str(tmp_path / "void"))
    assert capsys.readouterr().out == ""


def test_skipped_section_markers(monkeypatch, capsys):
    """Hardware sections skipped on TPU-preflight failure (or budget/
    timeout) leave an explicit machine-readable marker per section in
    the BENCH stream — the r02–r05 trajectory ambiguity (skips looked
    like gaps) closed. Markers carry no "metric" key, so the window
    fold-in and metric parsers ignore them."""
    import bench

    monkeypatch.delenv("BENCH_ONLY", raising=False)
    bench._emit_skipped_sections("tpu_preflight")
    lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()
             if l.startswith("{")]
    assert {l["section"] for l in lines} == set(bench._SECTIONS)
    assert all(l["skipped"] == "tpu_preflight" for l in lines)
    assert all("metric" not in l for l in lines)
    # BENCH_ONLY narrows the markers to the selected sections.
    monkeypatch.setenv("BENCH_ONLY", "lm,decode")
    bench._emit_skipped_sections("tpu_preflight")
    lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()
             if l.startswith("{")]
    assert {l["section"] for l in lines} == {"lm", "decode"}
    # Single-section form (watchdog-budget / timeout paths).
    monkeypatch.delenv("BENCH_ONLY", raising=False)
    bench._emit_skipped_sections("watchdog_budget", ["serve"])
    lines = [json.loads(l) for l in capsys.readouterr().out.splitlines()
             if l.startswith("{")]
    assert lines == [{"section": "serve", "skipped": "watchdog_budget"}]


def test_foreign_bench_detector_ignores_own_children(tmp_path):
    """The yield-to-driver scan is structural (argv[1] is the script
    path): text mentions of bench.py in other processes' cmdlines (e.g.
    the driver wrapper's prompt) must not trigger it."""
    script = tmp_path / "not_a_bench.py"
    script.write_text("import time; time.sleep(5)\n")
    p = subprocess.Popen(
        [sys.executable, str(script), "this mentions bench.py in an arg"]
    )
    try:
        assert wa._foreign_bench_running() is False
    finally:
        p.terminate()
        p.wait()
