"""SPMD tensor-parallel decode exactness (slow tier): the
{dense, paged} x {one-shot, chunked} bit-identity matrix at tp=2 plus
the supervisor crash/replay drill, and the pod-scale {tp=2, dp=2}
cells (ISSUE 20), via tools/serve_tp_check.py in a SUBPROCESS — a >1-device CPU needs
``--xla_force_host_platform_device_count`` set before jax imports,
which this (already-jax-initialized, single-device) test process cannot
do for itself. Slow-marked: tier-1 has no headroom for another
jit-heavy sweep (the fast tier-1 coverage of the sharding layer is
tests/test_serve_sharding.py); tools/serve_smoke.py runs this check in
its default pass."""

import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = [pytest.mark.serve, pytest.mark.slow]


def test_tp2_matrix_and_supervisor_replay_bit_identical():
    env = dict(
        os.environ,
        PYTHONPATH=REPO_ROOT + os.pathsep
        + os.environ.get("PYTHONPATH", ""),
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=2",
    )
    r = subprocess.run(
        [sys.executable,
         os.path.join(REPO_ROOT, "tools", "serve_tp_check.py"),
         "--tp", "2"],
        env=env, capture_output=True, text=True, timeout=900,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    out = r.stdout
    # Every matrix cell pinned, plus the spec leg and the replay drill.
    for cell in ("dense/oneshot", "dense/chunked", "paged/oneshot",
                 "paged/chunked", "spec/dense", "spec/paged",
                 "spec/paged-kv8"):
        assert f"serve_tp_check: {cell} ok" in out, out
    assert "supervisor replay ok" in out, out
    assert "serve_tp_check: OK" in out, out


def test_tp2_dp2_pod_scale_bit_identical():
    """Pod-scale decode (ISSUE 20): ONE engine over the 2-D {tp=2,
    dp=2} mesh — every layout cell bit-identical to the canonical tp
    oracle with zero post-warmup recompiles, shipped-KV and host-tier
    restores landing on the seating dp shard's block extent, and the
    supervisor rebuilding the 2-D mesh through the factory."""
    env = dict(
        os.environ,
        PYTHONPATH=REPO_ROOT + os.pathsep
        + os.environ.get("PYTHONPATH", ""),
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=4",
    )
    r = subprocess.run(
        [sys.executable,
         os.path.join(REPO_ROOT, "tools", "serve_tp_check.py"),
         "--tp", "2", "--dp", "2"],
        env=env, capture_output=True, text=True, timeout=900,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    out = r.stdout
    for cell in ("tpdp dense", "tpdp paged", "tpdp kv8", "tpdp pallas",
                 "tpdp ship ingest", "tpdp tier ingest",
                 "tpdp supervisor replay"):
        assert f"serve_tp_check: {cell} ok" in out, out
    assert "serve_tp_check: OK (tp=2, dp=2" in out, out
