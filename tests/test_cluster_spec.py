"""Golden tests for the topology/env injection layer (the TF_CONFIG analog).
Parity: controller_pod_test.go:87 TF_CONFIG content tests + golden-file
strategy from SURVEY.md §7 stage 3."""

import json

from tf_operator_tpu.api import constants
from tf_operator_tpu.controller import cluster_spec
from tf_operator_tpu.controller import status as status_engine
from tf_operator_tpu.api.types import JobConditionType, TPUJobStatus
from tf_operator_tpu.utils import testutil


class TestTFConfig:
    def test_cluster_spec_golden(self):
        job = testutil.new_tpujob(name="dist", worker=4, ps=2, chief=True)
        spec = cluster_spec.gen_cluster_spec(job)
        assert spec == {
            "chief": ["dist-chief-0:2222"],
            "ps": ["dist-ps-0:2222", "dist-ps-1:2222"],
            "worker": [
                "dist-worker-0:2222",
                "dist-worker-1:2222",
                "dist-worker-2:2222",
                "dist-worker-3:2222",
            ],
        }

    def test_tf_config_json(self):
        job = testutil.new_tpujob(name="dist", worker=2, ps=1)
        cfg = json.loads(cluster_spec.gen_tf_config(job, "PS", 0))
        assert cfg["task"] == {"type": "ps", "index": 0}
        assert cfg["environment"] == "cloud"

    def test_evaluator_excluded(self):
        job = testutil.new_tpujob(worker=1, evaluator=True)
        assert "evaluator" not in cluster_spec.gen_cluster_spec(job)

    def test_custom_port_respected(self):
        job = testutil.new_tpujob(worker=1)
        tmpl = job.spec.replica_specs["Worker"].template
        tmpl["spec"]["containers"][0]["ports"] = [
            {"name": constants.DEFAULT_PORT_NAME, "containerPort": 7777}
        ]
        assert cluster_spec.get_port(job, "Worker") == 7777
        assert cluster_spec.gen_cluster_spec(job)["worker"] == ["test-job-worker-0:7777"]

    def test_injection_only_default_container(self):
        job = testutil.new_tpujob(worker=1)
        tmpl = job.spec.replica_specs["Worker"].template
        tmpl["spec"]["containers"].append({"name": "sidecar", "image": "side"})
        out = cluster_spec.set_cluster_spec(tmpl, job, "Worker", 0)
        tf_env = [
            e for c in out["spec"]["containers"] if c["name"] == "sidecar"
            for e in c.get("env", [])
        ]
        assert tf_env == []

    def test_user_env_not_clobbered(self):
        job = testutil.new_tpujob(worker=1)
        tmpl = job.spec.replica_specs["Worker"].template
        tmpl["spec"]["containers"][0]["env"] = [
            {"name": constants.ENV_TF_CONFIG, "value": "user-set"}
        ]
        out = cluster_spec.set_cluster_spec(tmpl, job, "Worker", 0)
        env = {e["name"]: e["value"] for e in out["spec"]["containers"][0]["env"]}
        assert env[constants.ENV_TF_CONFIG] == "user-set"


class TestTPUEnv:
    def test_multislice_env(self):
        job = testutil.new_tpujob(name="ms", tpu_accelerator="v5e-16", num_slices=2)
        # 8 pods total: indices 0-3 slice 0, 4-7 slice 1.
        env = cluster_spec.gen_tpu_env(job, "Worker", 5)
        assert env[constants.ENV_TPU_WORKER_ID] == "1"
        assert env["MEGASCALE_SLICE_ID"] == "1"
        assert env["MEGASCALE_NUM_SLICES"] == "2"
        assert env[constants.ENV_TPU_WORKER_HOSTNAMES] == (
            "ms-worker-4,ms-worker-5,ms-worker-6,ms-worker-7"
        )
        assert env[constants.ENV_COORDINATOR_ADDRESS] == "ms-worker-4:2222"
        # The DCN rendezvous has its OWN port (job port + DCN_PORT_OFFSET):
        # on slice 0's worker 0 the in-slice jax coordinator and the
        # cross-slice coordinator share a pod and cannot share a bind.
        assert env["MEGASCALE_COORDINATOR_ADDRESS"] == "ms-worker-0:2223"

    def test_non_tpu_replica_no_env(self):
        job = testutil.new_tpujob(worker=2)
        assert cluster_spec.gen_tpu_env(job, "Worker", 0) == {}

    def test_hostnames_stable_ordering(self):
        job = testutil.new_tpujob(name="st", tpu_accelerator="v5e-16")
        env0 = cluster_spec.gen_tpu_env(job, "Worker", 0)
        env3 = cluster_spec.gen_tpu_env(job, "Worker", 3)
        assert (
            env0[constants.ENV_TPU_WORKER_HOSTNAMES]
            == env3[constants.ENV_TPU_WORKER_HOSTNAMES]
        )


class TestStatusEngine:
    def _cond(self, ctype):
        return status_engine.new_condition(ctype, "r", "m")

    def test_running_restarting_exclusive(self):
        st = TPUJobStatus()
        status_engine.set_condition(st, self._cond(JobConditionType.RUNNING))
        status_engine.set_condition(st, self._cond(JobConditionType.RESTARTING))
        types = [c.type for c in st.conditions if c.status == "True"]
        assert JobConditionType.RESTARTING in types
        assert JobConditionType.RUNNING not in types
        status_engine.set_condition(st, self._cond(JobConditionType.RUNNING))
        types = [c.type for c in st.conditions if c.status == "True"]
        assert JobConditionType.RUNNING in types
        assert JobConditionType.RESTARTING not in types

    def test_terminal_flips_running_false(self):
        st = TPUJobStatus()
        status_engine.set_condition(st, self._cond(JobConditionType.RUNNING))
        status_engine.set_condition(st, self._cond(JobConditionType.SUCCEEDED))
        running = [c for c in st.conditions if c.type == JobConditionType.RUNNING]
        assert running[0].status == "False"
        assert status_engine.is_succeeded(st)

    def test_failed_sticky(self):
        st = TPUJobStatus()
        status_engine.set_condition(st, self._cond(JobConditionType.FAILED))
        status_engine.set_condition(st, self._cond(JobConditionType.RUNNING))
        assert status_engine.is_failed(st)
        assert not status_engine.is_running(st)

    def test_created_then_running_coexist(self):
        st = TPUJobStatus()
        status_engine.set_condition(st, self._cond(JobConditionType.CREATED))
        status_engine.set_condition(st, self._cond(JobConditionType.RUNNING))
        types = [c.type for c in st.conditions if c.status == "True"]
        assert JobConditionType.CREATED in types and JobConditionType.RUNNING in types
