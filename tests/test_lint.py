"""tpulint: the project-specific static-analysis passes (ISSUE 12).

Three tiers:

- FIXTURES: each seeded-violation file under tests/lint_fixtures/ trips
  exactly its own pass; each clean twin trips nothing.
- UNITS: the class/lock model (cross-class edges through attribute
  types, ctor-param lock aliasing, @contextmanager extraction, the
  ``while not acquire(timeout=..)`` idiom), the waiver grammar, and the
  checks CLI (--list-passes/--select).
- WITNESS: the runtime Lock/Condition wrapper records acquisition-order
  edges that map onto static nodes, and is inert when the gate is off.

The repo-gate case itself (full pass set green over the whole tree)
lives in test_ci_tooling.py::test_repo_passes_its_own_checks.
"""

import os
import threading

import pytest

from tf_operator_tpu.harness.checks import (
    DEFAULT_PATHS,
    _py_files,
    list_passes,
    main as checks_main,
    run_checks,
)
from tf_operator_tpu.harness.lint import PASS_IDS, load_source_file
from tf_operator_tpu.harness.lint import classmodel, lockorder
from tf_operator_tpu.runtime import lockwitness

pytestmark = pytest.mark.lint

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = "tests/lint_fixtures"
TAXONOMY = "tf_operator_tpu/serve/resilience.py"


# ---------------------------------------------------------------------------
# fixtures: each trips exactly its pass; clean twins trip nothing
# ---------------------------------------------------------------------------

# fixture basename -> (extra paths to analyze with it, expected pass id)
_FIXTURE_MATRIX = {
    "lockorder_bad.py": ((), "lock-order"),
    "guarded_bad.py": ((), "guarded-attr"),
    "blocking_bad.py": ((), "blocking-under-lock"),
    "metrics_bad.py": ((), "metrics-registry"),
    # ISSUE 15 speculative-decode families: a drifted re-declaration of
    # tpu_serve_spec_accept_tokens / an unknown label on the rounds
    # counter must trip — dashboards key on these exact schemas.
    "metrics_spec_bad.py": ((), "metrics-registry"),
    "errors_bad.py": ((TAXONOMY,), "typed-error"),
    # Disaggregation wire codes (ISSUE 14): a typo'd ship_failed /
    # unknown prefill-pool code must trip — the two-stage router
    # dispatches on these strings.
    "errors_ship_bad.py": ((TAXONOMY,), "typed-error"),
    # Fleet-prefix pull codes (ISSUE 16): a typo'd prefix_not_found /
    # unknown degrade code must trip — the router's pull path degrades
    # to local prefill on these strings.
    "errors_prefix_bad.py": ((TAXONOMY,), "typed-error"),
    # KV-tier codes (ISSUE 17): a typo'd tier_miss / unknown warm-pull
    # degrade code must trip — the router degrades tier-pull failures
    # to local prefill on these strings.
    "errors_tier_bad.py": ((TAXONOMY,), "typed-error"),
    # Constrained-decoding codes (ISSUE 19): a typo'd invalid_grammar /
    # unknown finish-reason code must trip — the router hands a 400
    # back (never retries) on exactly this string.
    "errors_constrain_bad.py": ((TAXONOMY,), "typed-error"),
}


@pytest.mark.parametrize("name", sorted(_FIXTURE_MATRIX))
def test_fixture_trips_exactly_its_pass(name):
    extra, expected = _FIXTURE_MATRIX[name]
    problems = run_checks(
        (f"{FIXTURES}/{name}",) + extra, root=REPO_ROOT)
    assert problems, f"{name} tripped nothing"
    assert {p.pass_id for p in problems} == {expected}, [
        str(p) for p in problems
    ]
    assert all(p.path.endswith(name) for p in problems), [
        str(p) for p in problems
    ]


@pytest.mark.parametrize("name", [
    "lockorder_clean.py", "guarded_clean.py", "blocking_clean.py",
    "metrics_clean.py", "metrics_spec_clean.py", "errors_clean.py",
    "errors_ship_clean.py", "errors_prefix_clean.py",
    "errors_tier_clean.py", "errors_constrain_clean.py",
])
def test_clean_twin_trips_nothing(name):
    extra = (TAXONOMY,) if name.startswith("errors") else ()
    problems = run_checks((f"{FIXTURES}/{name}",) + extra, root=REPO_ROOT)
    assert [str(p) for p in problems] == []


def test_fixture_dir_is_excluded_from_the_repo_gate():
    files = _py_files(DEFAULT_PATHS, REPO_ROOT)
    assert not any("lint_fixtures" in f for f in files)


# ---------------------------------------------------------------------------
# waiver grammar
# ---------------------------------------------------------------------------


def _write(tmp_path, name, src):
    (tmp_path / name).write_text(src)
    return name


def test_justified_waiver_suppresses_finding(tmp_path):
    name = _write(tmp_path, "w.py", (
        "import threading\n"
        "import time\n\n\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n\n"
        "    def f(self):\n"
        "        with self._lock:\n"
        "            # lint: ok blocking-under-lock — seeded test waiver\n"
        "            time.sleep(0.01)\n"
    ))
    assert run_checks((name,), root=str(tmp_path)) == []


def test_waiver_without_reason_is_itself_a_finding(tmp_path):
    name = _write(tmp_path, "w.py", (
        "import threading\n"
        "import time\n\n\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n\n"
        "    def f(self):\n"
        "        with self._lock:\n"
        "            time.sleep(0.01)  # lint: ok blocking-under-lock\n"
    ))
    problems = run_checks((name,), root=str(tmp_path))
    # the waiver still applies (id matched) but is flagged as unjustified
    assert {p.pass_id for p in problems} == {"waiver"}
    assert "without justification" in problems[0].message


def test_waiver_multiple_ids_with_spaces_after_commas(tmp_path):
    name = _write(tmp_path, "w.py", (
        "import threading\n"
        "import time\n\n\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._x = 0\n\n"
        "    def w(self):\n"
        "        with self._lock:\n"
        "            self._x = 1\n\n"
        "    def f(self):\n"
        "        with self._lock:\n"
        "            # lint: ok blocking-under-lock, guarded-attr — seeded\n"
        "            time.sleep(self._x)\n"
    ))
    # the comma+space spelling must parse BOTH ids and keep the reason:
    # the blocking finding is waived and no "without justification"
    # waiver finding appears (the reason must not be eaten by the ids)
    assert run_checks((name,), root=str(tmp_path)) == []


def test_waiver_with_unknown_pass_id_is_flagged(tmp_path):
    name = _write(tmp_path, "w.py", (
        "x = 1  # lint: ok not-a-pass — whatever reason\n"
    ))
    problems = run_checks((name,), root=str(tmp_path))
    assert any(
        p.pass_id == "waiver" and "unknown pass" in p.message
        for p in problems
    )


def test_waiver_on_preceding_comment_line_covers_next_line(tmp_path):
    name = _write(tmp_path, "w.py", (
        "import threading\n\n\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._x = 0\n\n"
        "    def w(self):\n"
        "        with self._lock:\n"
        "            self._x = 1\n\n"
        "    def r(self):\n"
        "        # lint: ok guarded-attr — seeded: standalone-line waiver\n"
        "        return self._x\n"
    ))
    assert run_checks((name,), root=str(tmp_path)) == []


# ---------------------------------------------------------------------------
# model units
# ---------------------------------------------------------------------------


def _graph_for(tmp_path, src):
    name = _write(tmp_path, "m.py", src)
    files = [load_source_file(str(tmp_path / name), str(tmp_path))]
    return lockorder.static_lock_graph(files)


def test_cross_class_edge_through_attribute_type(tmp_path):
    g = _graph_for(tmp_path, (
        "import threading\n\n\n"
        "class Inner:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n\n"
        "    def poke(self):\n"
        "        with self._lock:\n"
        "            pass\n\n\n"
        "class Outer:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._inner = Inner()\n\n"
        "    def drive(self):\n"
        "        with self._lock:\n"
        "            self._inner.poke()\n"
    ))
    assert ("m.Outer._lock", "m.Inner._lock") in g.edges


def test_ctor_param_lock_alias_merges_nodes(tmp_path):
    g = _graph_for(tmp_path, (
        "import threading\n\n\n"
        "class Worker:\n"
        "    def __init__(self, device_lock=None):\n"
        "        self._device_lock = device_lock or threading.Lock()\n\n\n"
        "class Boss:\n"
        "    def __init__(self):\n"
        "        self._device_lock = threading.Lock()\n"
        "        self._w = Worker(device_lock=self._device_lock)\n"
    ))
    # both spellings canonicalize to ONE node
    assert g.canon("m.Worker._device_lock") == \
        g.canon("m.Boss._device_lock")


def test_while_acquire_and_ctxmgr_idioms(tmp_path):
    g = _graph_for(tmp_path, (
        "import contextlib\n"
        "import threading\n\n\n"
        "class Sched:\n"
        "    def __init__(self):\n"
        "        self._device_lock = threading.Lock()\n"
        "        self._cond = threading.Condition()\n\n"
        "    @contextlib.contextmanager\n"
        "    def _device(self):\n"
        "        while not self._device_lock.acquire(timeout=0.1):\n"
        "            pass\n"
        "        try:\n"
        "            yield\n"
        "        finally:\n"
        "            self._device_lock.release()\n\n"
        "    def step(self):\n"
        "        with self._device():\n"
        "            with self._cond:\n"
        "                pass\n"
    ))
    assert ("m.Sched._device_lock", "m.Sched._cond") in g.edges


def test_cycle_detection_reports_both_orders(tmp_path):
    name = _write(tmp_path, "m.py", (
        "import threading\n\n"
        "_A = threading.Lock()\n"
        "_B = threading.Lock()\n\n\n"
        "def f():\n"
        "    with _A:\n"
        "        with _B:\n"
        "            pass\n\n\n"
        "def g():\n"
        "    with _B:\n"
        "        with _A:\n"
        "            pass\n"
    ))
    files = [load_source_file(str(tmp_path / name), str(tmp_path))]
    proj = classmodel.build_project(files)
    problems = lockorder.run(files, proj)
    assert problems and all(p.pass_id == "lock-order" for p in problems)
    assert any("cycle" in p.message for p in problems)


def test_creation_sites_name_the_defining_class(tmp_path):
    g = _graph_for(tmp_path, (
        "import threading\n\n\n"
        "class Base:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n\n"
        "    def f(self):\n"
        "        with self._lock:\n"
        "            pass\n\n\n"
        "class Child(Base):\n"
        "    def g(self):\n"
        "        with self._lock:\n"
        "            return 1\n"
    ))
    # the site maps to Base (the creator), and Child's use resolves to
    # the same node
    assert "m.Base._lock" in g.sites.values()
    assert "m.Child._lock" not in g.sites.values()


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def test_list_passes_catalog():
    ids = [pid for pid, _doc in list_passes()]
    assert ids[:2] == ["syntax", "unused-import"]
    assert list(PASS_IDS) == ids[2:]
    assert checks_main(["--list-passes"]) == 0


def test_select_restricts_passes(tmp_path):
    name = _write(tmp_path, "w.py", (
        "import os\n"   # unused import AND a blocking violation
        "import threading\n"
        "import time\n\n\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n\n"
        "    def f(self):\n"
        "        with self._lock:\n"
        "            time.sleep(0.01)\n"
    ))
    only_blocking = run_checks((name,), root=str(tmp_path),
                               select=("blocking-under-lock",))
    assert {p.pass_id for p in only_blocking} == {"blocking-under-lock"}
    only_imports = run_checks((name,), root=str(tmp_path),
                              select=("unused-import",))
    assert {p.pass_id for p in only_imports} == {"unused-import"}
    with pytest.raises(ValueError, match="unknown pass"):
        run_checks((name,), root=str(tmp_path), select=("nope",))


# ---------------------------------------------------------------------------
# runtime witness
# ---------------------------------------------------------------------------


def test_witness_disabled_is_inert(monkeypatch):
    monkeypatch.delenv(lockwitness.WITNESS_ENV, raising=False)
    before = threading.Lock
    assert lockwitness.install() is None
    assert threading.Lock is before
    assert lockwitness.current() is None


def test_witness_records_edges_that_map_onto_static_nodes():
    wit = lockwitness.install(force=True)
    try:
        # deterministic nesting from inside the package frame (probe),
        # plus a per-instance package lock created after install so the
        # creation-site -> static-node mapping is exercised regardless
        # of which modules earlier tests already imported
        a, b = lockwitness.probe()
        from tf_operator_tpu.fleet.membership import FleetMembership
        m = FleetMembership(name="lint-test")
        m.register("r1", "h:1")
        m.deregister("r1")
    finally:
        lockwitness.uninstall()
    assert threading.Lock is lockwitness._real_Lock
    # the probe's nested acquisition was recorded (raw edge by site)
    assert (a.site, b.site) in wit.edges
    report = wit.check_against_static(REPO_ROOT)
    assert report["acquisitions"] > 0 and report["wrapped"] > 0
    # probe locks are function-locals — the model names those sites
    # too, so the probe edge arrives MAPPED and matches its own static
    # edge (probe's `with a: with b:` is in the analyzed tree)
    probe_edge = (
        "tf_operator_tpu.runtime.lockwitness.<module>.probe.a",
        "tf_operator_tpu.runtime.lockwitness.<module>.probe.b",
    )
    assert probe_edge in report["observed"]
    assert report["unmapped"] == []
    assert report["violations"] == []
    assert report["cycles"] == []
    assert report["self_site"] == []
    # creation-site mapping: the membership instance lock created after
    # install maps onto its static node
    graph = lockwitness._static_graph(REPO_ROOT)
    rels = {
        (os.path.relpath(f, REPO_ROOT).replace(os.sep, "/"), line)
        for (f, line) in wit.sites
    }
    mapped = {graph.sites.get(s) for s in rels} - {None}
    assert "tf_operator_tpu.fleet.membership.FleetMembership._lock" \
        in mapped


def test_witness_reentrant_rlock_is_not_an_edge():
    wit = lockwitness.install(force=True)
    try:
        from tf_operator_tpu.controller.workqueue import RateLimitingQueue
        q = RateLimitingQueue()
        with q._cond:
            with q._cond:   # Condition is RLock-backed: legal re-entry
                pass
    finally:
        lockwitness.uninstall()
    assert wit.total_acquisitions > 0
    assert all(a != b for (a, b) in wit.edges), wit.edges
