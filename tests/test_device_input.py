"""Device-resident input (train/device_input.py): correctness of the
on-device gather + random-crop + hflip sampler and the fused train loop.

The crop test encodes each pixel's (record, row, col) into its value so
the sampled output proves exactly which window of which record it came
from — no reliance on replicating the PRNG draws outside the module.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tf_operator_tpu.train.device_input import (
    load_records_numpy,
    make_resident_epoch_sampler,
    make_resident_epoch_train_loop,
    make_resident_sampler,
    make_resident_train_loop,
)

R, CROP, N_REC, BATCH = 12, 8, 5, 16


def coded_images() -> np.ndarray:
    """[N, R, R, 3] uint8 where channel 0 = record index, channel 1 =
    row, channel 2 = col — every pixel self-describes its origin."""
    imgs = np.zeros((N_REC, R, R, 3), np.uint8)
    for rec in range(N_REC):
        imgs[rec, :, :, 0] = rec
        imgs[rec, :, :, 1] = np.arange(R)[:, None]
        imgs[rec, :, :, 2] = np.arange(R)[None, :]
    return imgs


def denormalize(img_bf16) -> np.ndarray:
    return np.asarray(
        img_bf16.astype(jnp.float32) * 127.5 + 127.5
    ).round().astype(np.int32)


def test_sampler_crops_are_contiguous_windows_with_optional_flip():
    imgs = coded_images()
    labels = np.arange(N_REC, dtype=np.int32) * 7
    sample = make_resident_sampler(
        jnp.asarray(imgs), jnp.asarray(labels), BATCH, CROP
    )
    out = sample(jax.random.PRNGKey(3))
    assert out["image"].shape == (BATCH, CROP, CROP, 3)
    assert out["image"].dtype == jnp.bfloat16
    px = denormalize(out["image"])  # [B, CROP, CROP, 3] ints
    lab = np.asarray(out["label"])
    margin = R - CROP
    for b in range(BATCH):
        rec = px[b, 0, 0, 0]
        assert 0 <= rec < N_REC
        assert lab[b] == (rec * 7) % 1000
        # rows must be a contiguous window [y0, y0+CROP)
        y0 = px[b, 0, 0, 1]
        assert 0 <= y0 <= margin
        np.testing.assert_array_equal(
            px[b, :, 0, 1], np.arange(y0, y0 + CROP)
        )
        # cols: ascending window (unflipped) or descending (flipped)
        cols = px[b, 0, :, 2]
        x0 = cols.min()
        assert 0 <= x0 <= margin
        ascending = np.arange(x0, x0 + CROP)
        assert (
            np.array_equal(cols, ascending)
            or np.array_equal(cols, ascending[::-1])
        )
        # every pixel of the sample comes from the same record
        assert (px[b, :, :, 0] == rec).all()


def test_sampler_uses_crop_offsets_and_flips_across_batch():
    # With margin 4 and 64 draws, offsets and flips must show variety —
    # a sampler that ignores its PRNG would produce constants.
    imgs = coded_images()
    labels = np.zeros(N_REC, np.int32)
    sample = make_resident_sampler(
        jnp.asarray(imgs), jnp.asarray(labels), 64, CROP
    )
    px = denormalize(sample(jax.random.PRNGKey(0))["image"])
    y0s = {int(px[b, 0, 0, 1]) for b in range(64)}
    flips = {
        bool(px[b, 0, 0, 2] > px[b, 0, -1, 2]) for b in range(64)
    }
    assert len(y0s) > 1
    assert flips == {True, False}


def test_sampler_deterministic_per_key():
    imgs = coded_images()
    labels = np.zeros(N_REC, np.int32)
    sample = make_resident_sampler(
        jnp.asarray(imgs), jnp.asarray(labels), BATCH, CROP
    )
    a = sample(jax.random.PRNGKey(5))
    b = sample(jax.random.PRNGKey(5))
    c = sample(jax.random.PRNGKey(6))
    np.testing.assert_array_equal(
        np.asarray(a["image"], np.float32), np.asarray(b["image"], np.float32)
    )
    assert not np.array_equal(
        np.asarray(a["image"], np.float32), np.asarray(c["image"], np.float32)
    )


def test_sampler_rejects_too_small_records():
    imgs = jnp.zeros((2, 4, 4, 3), jnp.uint8)
    with pytest.raises(ValueError, match="smaller than crop"):
        make_resident_sampler(imgs, jnp.zeros((2,), jnp.int32), 4, 8)


def test_load_records_numpy_roundtrip(tmp_path):
    rec_size = 6
    img_bytes = rec_size * rec_size * 3
    rng = np.random.default_rng(0)
    n = 4
    recs = rng.integers(0, 256, (n, img_bytes + 1), dtype=np.uint8)
    path = str(tmp_path / "recs.bin")
    recs.tofile(path)
    images, labels = load_records_numpy(path, img_bytes + 1, rec_size)
    assert images.shape == (n, rec_size, rec_size, 3)
    np.testing.assert_array_equal(
        images.reshape(n, -1), recs[:, :img_bytes]
    )
    np.testing.assert_array_equal(labels, recs[:, img_bytes].astype(np.int32))
    with pytest.raises(ValueError, match="not a multiple"):
        load_records_numpy(path, img_bytes, rec_size)


def test_epoch_sampler_visits_every_record_once_per_epoch():
    """Exact epoch semantics: with N=6 records and batch 2, every 3
    consecutive batches cover all records exactly once; the next epoch
    uses a different order (new permutation)."""
    n, b = 6, 2
    imgs = np.zeros((n, CROP, CROP, 3), np.uint8)
    for rec in range(n):
        imgs[rec, :, :, 0] = rec  # channel 0 encodes the record id
    labels = np.arange(n, dtype=np.int32)
    sample, state = make_resident_epoch_sampler(
        jnp.asarray(imgs), jnp.asarray(labels), b, CROP
    )
    key = jax.random.PRNGKey(0)
    epochs = []
    for _ in range(3):  # 3 epochs of 3 batches
        seen = []
        for _ in range(n // b):
            key, sub = jax.random.split(key)
            out, state = sample(sub, state)
            seen.extend(int(x) for x in np.asarray(out["label"]))
        assert sorted(seen) == list(range(n)), seen
        epochs.append(tuple(seen))
    # permutations differ across epochs (astronomically unlikely to
    # collide three times; a constant order would mean no reshuffle)
    assert len(set(epochs)) > 1, epochs


def test_epoch_sampler_requires_divisible_batch():
    imgs = jnp.zeros((5, CROP, CROP, 3), jnp.uint8)
    with pytest.raises(ValueError, match="divisible"):
        make_resident_epoch_sampler(imgs, jnp.zeros((5,), jnp.int32), 2, CROP)


def test_epoch_train_loop_spans_epoch_boundary():
    """A fused scan longer than one epoch crosses the reshuffle cond
    inside jit; labels stay valid and the sampler state advances."""
    import optax

    n, b = 4, 2
    imgs = coded_images()[:n]
    labels = (np.arange(n) % 3).astype(np.int32)
    sample, sstate = make_resident_epoch_sampler(
        jnp.asarray(imgs), jnp.asarray(labels), b, CROP, num_classes=3
    )
    tx = optax.sgd(0.1)
    params = {"w": jnp.zeros((CROP * CROP * 3, 3), jnp.float32)}
    opt_state = tx.init(params)

    def step(state, batch):
        params, opt_state = state

        def loss_fn(p):
            x = batch["image"].astype(jnp.float32).reshape(b, -1)
            return optax.softmax_cross_entropy_with_integer_labels(
                x @ p["w"], batch["label"]
            ).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return (optax.apply_updates(params, updates), opt_state), {
            "loss": loss
        }

    fused = make_resident_epoch_train_loop(step, sample, n_steps=5)
    state, metrics, key, sstate = fused(
        (params, opt_state), jax.random.PRNGKey(1), sstate
    )
    assert np.isfinite(float(metrics["loss"]))
    # 5 steps of batch 2 over 4 records: cursor is 5*2 mod epoch pacing;
    # state must be a valid (perm, cursor) pair with cursor % b == 0
    perm, cursor = sstate
    assert sorted(np.asarray(perm).tolist()) == list(range(n))
    assert int(cursor) % b == 0


def test_resident_train_loop_runs_and_advances_key():
    """End-to-end: fused scan of (sample → SGD step) on a tiny MLP
    classifier; state advances, loss finite, key advances so calls
    continue the stream."""
    import optax

    imgs = coded_images()
    labels = (np.arange(N_REC) % 3).astype(np.int32)
    sample = make_resident_sampler(
        jnp.asarray(imgs), jnp.asarray(labels), 8, CROP, num_classes=3
    )

    def init_params(key):
        k1, k2 = jax.random.split(key)
        return {
            "w": jax.random.normal(k1, (CROP * CROP * 3, 3), jnp.float32)
            * 0.01,
            "b": jnp.zeros((3,), jnp.float32),
        }

    tx = optax.sgd(0.1)
    params = init_params(jax.random.PRNGKey(0))
    opt_state = tx.init(params)

    def step(state, batch):
        params, opt_state = state

        def loss_fn(p):
            x = batch["image"].astype(jnp.float32).reshape(8, -1)
            logits = x @ p["w"] + p["b"]
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, batch["label"]
            ).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return (optax.apply_updates(params, updates), opt_state), {
            "loss": loss
        }

    fused = make_resident_train_loop(step, sample, n_steps=3)
    key = jax.random.PRNGKey(42)
    state, metrics, key2 = fused((params, opt_state), key)
    assert np.isfinite(float(metrics["loss"]))
    assert not np.array_equal(np.asarray(key), np.asarray(key2))
    # second call continues (donated state, advanced key) without retrace
    state, metrics, key3 = fused(state, key2)
    assert np.isfinite(float(metrics["loss"]))
    assert not np.array_equal(np.asarray(key2), np.asarray(key3))
