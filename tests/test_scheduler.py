"""Gang-admission scheduler tests: queue ordering/aging, quota accounting,
topology fit, preemption victim selection, and the GangScheduler admission
pipeline (gate → admit → release → recover) against the in-memory cluster.

The chaos-grade all-or-nothing proofs (controller killed mid-release, two
jobs oversubscribing the fleet on both backends) live in test_chaos.py.
"""

import json

import pytest

from tf_operator_tpu.api import constants
from tf_operator_tpu.controller.tpujob_controller import TPUJobController
from tf_operator_tpu.runtime import objects
from tf_operator_tpu.runtime.client import Invalid
from tf_operator_tpu.runtime.events import FakeRecorder
from tf_operator_tpu.runtime.memcluster import InMemoryCluster
from tf_operator_tpu.scheduler import (
    GATE_NAME,
    AdmissionQueue,
    Gang,
    GangScheduler,
    Quota,
    QuotaLedger,
    SchedulerConfig,
    TopologyPlacer,
    gang_from_job,
    is_gated,
    parse_capacity,
    resolve_priority,
    select_victims,
)
from tf_operator_tpu.scheduler.gang import (
    ANNOTATION_PREEMPTED_AT,
    ANNOTATION_STATE,
    STATE_ADMITTED,
    STATE_QUEUED,
    SliceRequest,
    ungate_patch,
)
from tf_operator_tpu.scheduler.placement import CapacityError
from tf_operator_tpu.utils import testutil

pytestmark = pytest.mark.scheduler


def mk_gang(name, priority=0, chips=8, dims=(2, 2, 2), pods=2, ns="default",
            enqueued_at=1000.0, gen="v4"):
    return Gang(
        namespace=ns,
        name=name,
        uid=f"uid-{name}",
        priority_class=str(priority),
        priority=priority,
        pod_count=pods,
        slices=[SliceRequest(gen, dims, chips)],
        enqueued_at=enqueued_at,
    )


def tpu_job(name, accel="v4-8", priority_class=None, ns="default"):
    job = testutil.new_tpujob(name=name, namespace=ns, tpu_accelerator=accel)
    if priority_class:
        job.spec.scheduling.priority_class = priority_class
    return job


# ---------------------------------------------------------------------------
# queue.py: ordering, aging, quota
# ---------------------------------------------------------------------------

def test_queue_orders_by_priority_then_fifo():
    q = AdmissionQueue(aging_rate=0.0)
    q.add(mk_gang("low", priority=-100, enqueued_at=1.0))
    q.add(mk_gang("first-default", priority=0, enqueued_at=2.0))
    q.add(mk_gang("second-default", priority=0, enqueued_at=3.0))
    q.add(mk_gang("crit", priority=1000, enqueued_at=99.0))
    names = [g.name for g in q.ordered(now=100.0)]
    assert names == ["crit", "first-default", "second-default", "low"]


def test_queue_aging_lets_old_low_priority_overtake():
    q = AdmissionQueue(aging_rate=1.0)
    q.add(mk_gang("patient-default", priority=0, enqueued_at=0.0))
    q.add(mk_gang("fresh-high", priority=100, enqueued_at=200.0))
    # At t=200 the default gang has 200 aging points vs high's 100.
    assert [g.name for g in q.ordered(now=200.0)] == [
        "patient-default", "fresh-high"
    ]
    # Early on, static priority still wins.
    assert [g.name for g in q.ordered(now=50.0)] == [
        "fresh-high", "patient-default"
    ]


def test_quota_ledger_chips_and_slices_axes():
    ledger = QuotaLedger({"teama": Quota(chips=16, slices=2)})
    g1 = mk_gang("a1", chips=8, ns="teama")
    g2 = mk_gang("a2", chips=8, ns="teama")
    g3 = mk_gang("a3", chips=8, ns="teama")
    assert ledger.fits(g1)
    ledger.charge(g1)
    assert ledger.fits(g2)
    ledger.charge(g2)
    # Third gang busts both chip (24 > 16) and slice (3 > 2) budgets.
    assert not ledger.fits(g3)
    ledger.refund(g1)
    assert ledger.fits(g3)
    # Un-quota'd namespaces are unlimited.
    assert ledger.fits(mk_gang("other", chips=10 ** 6, ns="elsewhere"))


# ---------------------------------------------------------------------------
# gang.py: priority + gang construction + gate helpers
# ---------------------------------------------------------------------------

def test_resolve_priority_names_numbers_unknown():
    assert resolve_priority("critical") == 1000
    assert resolve_priority("low") == -100
    assert resolve_priority("750") == 750
    assert resolve_priority("no-such-class") == 0
    assert resolve_priority(None) == 0


def test_gang_from_job_counts_pods_and_slices():
    job = testutil.new_tpujob(tpu_accelerator="v4-8", ps=2)
    gang = gang_from_job(job)
    # v4-8 = 8 chips over 2 hosts; PS pods ride the gang without chips.
    assert gang.pod_count == 4  # 2 slice hosts + 2 PS
    assert gang.num_slices == 1
    assert gang.total_chips == 8
    assert gang.slices[0].dims == (2, 2, 2)


def test_gate_helpers_roundtrip():
    pod = {"spec": {"schedulingGates": [{"name": GATE_NAME},
                                        {"name": "other/gate"}]}}
    assert is_gated(pod)
    patch = ungate_patch(pod)
    # Merge-patch preserves the foreign gate while removing ours.
    assert patch == {"spec": {"schedulingGates": [{"name": "other/gate"}]}}
    assert not is_gated({"spec": {}})


# ---------------------------------------------------------------------------
# placement.py: capacity parsing + contiguous fit
# ---------------------------------------------------------------------------

def test_parse_capacity_spec():
    cap = parse_capacity("v5e=4x8, v4=2x2x4")
    assert cap == {"v5e": (4, 8), "v4": (2, 2, 4)}
    with pytest.raises(CapacityError):
        parse_capacity("v99=4x4")


def test_placement_rotation_fits_transposed_block():
    placer = TopologyPlacer({"v5e": (2, 4)})
    # A 4x2 request only fits the 2x4 mesh rotated.
    got = placer.try_fit([SliceRequest("v5e", (4, 2), 8)])
    assert got is not None and got[0].dims in ((2, 4), (4, 2))
    assert got[0].chips == 8


def test_placement_all_or_nothing_and_release():
    placer = TopologyPlacer({"v5e": (2, 4)})
    two = [SliceRequest("v5e", (2, 2), 4), SliceRequest("v5e", (2, 2), 4)]
    placements = placer.try_fit(two)
    assert placements is not None
    placer.commit(placements)
    assert placer.chips_in_use() == {"v5e": 8}
    # Mesh is full: nothing more fits — and the failed fit must not leak
    # tentative cells.
    assert placer.try_fit([SliceRequest("v5e", (1, 1), 1)]) is None
    assert placer.chips_in_use() == {"v5e": 8}
    placer.release(placements[:1])
    assert placer.try_fit([SliceRequest("v5e", (2, 2), 4)]) is not None


def test_placement_unknown_generation_does_not_fit():
    placer = TopologyPlacer({"v5e": (4, 4)})
    assert placer.try_fit([SliceRequest("v4", (2, 2, 2), 8)]) is None


def test_placement_unbounded_admits_everything():
    placer = TopologyPlacer(None)
    got = placer.try_fit([SliceRequest("v4", (8, 8, 8), 512)])
    assert got is not None and placer.unbounded


# ---------------------------------------------------------------------------
# preemption.py: victim selection
# ---------------------------------------------------------------------------

def _committed(placer, gang):
    placements = placer.try_fit(gang.slices)
    assert placements is not None
    gang.placements = placements
    gang.state = STATE_ADMITTED
    placer.commit(placements)
    return gang


def test_preemption_only_strictly_lower_priority():
    placer = TopologyPlacer({"v4": (2, 2, 2)})
    ledger = QuotaLedger()
    equal = _committed(placer, mk_gang("equal", priority=100))
    pending = mk_gang("pending", priority=100)
    assert select_victims(pending, [equal], placer, ledger) is None


def test_preemption_picks_minimal_youngest_lowest():
    # Mesh fits two 2x2x2 slices; both are held by low-priority gangs.
    placer = TopologyPlacer({"v4": (2, 2, 4)})
    ledger = QuotaLedger()
    old = _committed(placer, mk_gang("old-low", priority=-100))
    old.admitted_at = 100.0
    young = _committed(placer, mk_gang("young-low", priority=-100))
    young.admitted_at = 200.0
    ledger.charge(old)
    ledger.charge(young)
    pending = mk_gang("pending-high", priority=100)
    victims = select_victims(pending, [old, young], placer, ledger)
    # One eviction suffices; the youngest (cheapest to redo) is chosen.
    assert [v.name for v in victims] == ["young-low"]


def test_preemption_none_when_even_all_victims_insufficient():
    placer = TopologyPlacer({"v4": (2, 2, 2)})
    ledger = QuotaLedger()
    low = _committed(placer, mk_gang("low", priority=-100))
    ledger.charge(low)
    # Pending wants more than the whole mesh: no victim set can help.
    pending = mk_gang("huge", priority=100, dims=(4, 4, 4), chips=64)
    assert select_victims(pending, [low], placer, ledger) is None


# ---------------------------------------------------------------------------
# core.py: the admission pipeline on the in-memory cluster
# ---------------------------------------------------------------------------

def mk_scheduler(client, capacity=None, quotas=None, aging=0.0):
    wakes = []
    sched = GangScheduler(
        client,
        SchedulerConfig(capacity=capacity, quotas=quotas or {},
                        aging_rate=aging),
        recorder=FakeRecorder(),
    )
    sched.attach(client, wakeup=wakes.append)
    return sched, wakes


def submit(client, job):
    created = client.create(objects.TPUJOBS, job.to_dict())
    job.metadata.resource_version = str(
        objects.meta(created).get("resourceVersion", "")
    )
    job.metadata.uid = objects.uid_of(created) or job.metadata.uid
    return job


def test_unbounded_scheduler_admits_immediately():
    client = InMemoryCluster()
    sched, _ = mk_scheduler(client)
    job = submit(client, tpu_job("free"))
    decision = sched.reconcile_gang(job)
    assert decision.admitted and decision.state == STATE_ADMITTED
    stored = client.get(objects.TPUJOBS, "default", "free")
    assert stored["metadata"]["annotations"][ANNOTATION_STATE] == STATE_ADMITTED


def test_capacity_queues_then_admits_on_release():
    client = InMemoryCluster()
    sched, wakes = mk_scheduler(client, capacity={"v4": (2, 2, 2)})
    first = submit(client, tpu_job("first"))
    second = submit(client, tpu_job("second"))
    assert sched.reconcile_gang(first).admitted
    decision = sched.reconcile_gang(second)
    assert not decision.admitted and decision.state == STATE_QUEUED
    ann = client.get(objects.TPUJOBS, "default", "second")["metadata"][
        "annotations"]
    assert ann[ANNOTATION_STATE] == STATE_QUEUED
    # First job finishes: its capacity refund pumps the queue and wakes the
    # controller for the newly admitted key.
    sched.release_job(first.key)
    assert "default/second" in wakes
    assert sched.reconcile_gang(second).admitted
    snap = sched.snapshot()
    assert [g["key"] for g in snap["admitted"]] == ["default/second"]
    assert snap["queued"] == []
    assert snap["chipsInUse"] == {"v4": 8}


def test_quota_blocks_admission_without_capacity_pressure():
    client = InMemoryCluster()
    sched, _ = mk_scheduler(client, quotas={"default": Quota(chips=8)})
    a = submit(client, tpu_job("qa"))
    b = submit(client, tpu_job("qb"))
    assert sched.reconcile_gang(a).admitted
    # Unbounded fleet, but the namespace budget (8 chips) is spent.
    assert not sched.reconcile_gang(b).admitted
    sched.release_job(a.key)
    assert sched.reconcile_gang(b).admitted


def _create_gang_pods(client, job, gated=True):
    """Pods as the controller's build_pod creates them (gate stamped)."""
    pods = []
    topo_pods = 2  # v4-8 = 2 hosts
    for i in range(topo_pods):
        pod = testutil.new_pod_for_job(job, "Worker", i, objects.PENDING)
        if gated:
            pod["spec"]["schedulingGates"] = [{"name": GATE_NAME}]
        pod["metadata"]["labels"][constants.LABEL_JOB_NAME] = (
            job.metadata.name
        )
        pods.append(client.create(objects.PODS, pod))
    return pods


def test_gated_pod_cannot_run_until_released():
    client = InMemoryCluster()
    sched, _ = mk_scheduler(client)
    job = submit(client, tpu_job("atomic"))
    assert sched.reconcile_gang(job).admitted
    _create_gang_pods(client, job)

    # The store-level gate: a kubelet write of Running on a gated pod is
    # refused — this is what makes a crash between create and release safe.
    pod = client.list(objects.PODS, "default")[0]
    objects.set_pod_phase(pod, objects.RUNNING)
    with pytest.raises(Invalid):
        client.update_status(objects.PODS, pod)
    assert client.gate_rejections == 1

    assert sched.release_gang(job)
    pods = client.list(objects.PODS, "default")
    assert pods and all(not is_gated(p) for p in pods)
    # Released pods run normally.
    objects.set_pod_phase(pods[0], objects.RUNNING)
    client.update_status(objects.PODS, pods[0])


def test_release_gang_waits_for_full_pod_set():
    client = InMemoryCluster()
    sched, _ = mk_scheduler(client)
    job = submit(client, tpu_job("straggler"))
    assert sched.reconcile_gang(job).admitted
    pod = testutil.new_pod_for_job(job, "Worker", 0, objects.PENDING)
    pod["spec"]["schedulingGates"] = [{"name": GATE_NAME}]
    pod["metadata"]["labels"][constants.LABEL_JOB_NAME] = job.metadata.name
    client.create(objects.PODS, pod)
    # 1 of 2 expected pods: release must refuse (all-pods-first rule).
    assert not sched.release_gang(job)
    assert all(is_gated(p) for p in client.list(objects.PODS, "default"))


def test_admission_recovery_after_scheduler_restart():
    client = InMemoryCluster()
    sched1, _ = mk_scheduler(client, capacity={"v4": (2, 2, 2)})
    job = submit(client, tpu_job("survivor"))
    assert sched1.reconcile_gang(job).admitted

    # New scheduler incarnation (controller restart): the persisted
    # admission is recovered — not re-queued — and the ledger is recharged
    # so a competing gang still sees a full fleet.
    sched2, _ = mk_scheduler(client, capacity={"v4": (2, 2, 2)})
    refetched = tpu_job("survivor")
    refetched.metadata.annotations = dict(
        client.get(objects.TPUJOBS, "default", "survivor")["metadata"][
            "annotations"]
    )
    assert sched2.reconcile_gang(refetched).admitted
    rival = submit(client, tpu_job("rival"))
    assert not sched2.reconcile_gang(rival).admitted
    assert sched2.snapshot()["chipsInUse"] == {"v4": 8}


def test_preemption_evicts_whole_gang_and_requeues():
    client = InMemoryCluster()
    sched, wakes = mk_scheduler(client, capacity={"v4": (2, 2, 2)})
    low = submit(client, tpu_job("low", priority_class="low"))
    assert sched.reconcile_gang(low).admitted
    _create_gang_pods(client, low, gated=False)
    assert len(client.list(objects.PODS, "default")) == 2

    crit = submit(client, tpu_job("crit", priority_class="critical"))
    decision = sched.reconcile_gang(crit)
    assert decision.admitted, "preemption must admit within the same pass"
    # The victim was evicted WHOLE and requeued as a gang.
    assert client.list(objects.PODS, "default") == []
    ann = client.get(objects.TPUJOBS, "default", "low")["metadata"][
        "annotations"]
    assert ann[ANNOTATION_STATE] == STATE_QUEUED
    assert ANNOTATION_PREEMPTED_AT in ann  # checkpoint signal landed
    snap = sched.snapshot()
    assert [g["key"] for g in snap["queued"]] == ["default/low"]
    assert [g["key"] for g in snap["admitted"]] == ["default/crit"]
    assert snap["queued"][0]["requeues"] == 1
    assert "default/low" in wakes  # victim's controller key re-enqueued


def test_preemption_disabled_leaves_victims_alone():
    client = InMemoryCluster()
    sched = GangScheduler(
        client,
        SchedulerConfig(capacity={"v4": (2, 2, 2)}, preemption=False),
    )
    low = submit(client, tpu_job("low2", priority_class="low"))
    assert sched.reconcile_gang(low).admitted
    crit = submit(client, tpu_job("crit2", priority_class="critical"))
    assert not sched.reconcile_gang(crit).admitted
    assert [g["key"] for g in sched.snapshot()["admitted"]] == [
        "default/low2"
    ]


# ---------------------------------------------------------------------------
# Controller integration: sync → gated create → same-pass release
# ---------------------------------------------------------------------------

def sync_once(tc, job):
    tc.job_informer.sync_now()
    tc.pod_informer.sync_now()
    tc.service_informer.sync_now()
    return tc.sync_job(job.key)


def test_controller_sync_creates_gated_then_releases_same_pass():
    client = InMemoryCluster()
    tc = TPUJobController(client, recorder=FakeRecorder())
    job = submit(client, tpu_job("pipeline"))
    sync_once(tc, job)
    pods = client.list(objects.PODS, "default")
    assert len(pods) == 2
    # The unbounded default admits in the same pass, so the gates are
    # already lifted — but they provably WERE stamped (release counted).
    assert all(not is_gated(p) for p in pods)
    ann = client.get(objects.TPUJOBS, "default", "pipeline")["metadata"][
        "annotations"]
    assert ann[ANNOTATION_STATE] == STATE_ADMITTED


def test_queued_job_creates_no_pods_and_no_pdb():
    client = InMemoryCluster()
    sched = GangScheduler(config=SchedulerConfig(capacity={"v4": (2, 2, 2)}))
    tc = TPUJobController(client, recorder=FakeRecorder(), scheduler=sched)
    winner = submit(client, tpu_job("winner"))
    loser = submit(client, tpu_job("loser"))  # same priority: queues
    sync_once(tc, winner)
    sync_once(tc, loser)
    pods = client.list(objects.PODS, "default")
    assert {p["metadata"]["labels"][constants.LABEL_JOB_NAME]
            for p in pods} == {"winner"}
    # Satellite: no orphan PDB for a never-admitted gang.
    assert client.list(objects.PDBS, "default", {}) == [] or all(
        p["metadata"]["name"] != "loser-gang"
        for p in client.list(objects.PDBS, "default")
    )
    ann = client.get(objects.TPUJOBS, "default", "loser")["metadata"][
        "annotations"]
    assert ann[ANNOTATION_STATE] == STATE_QUEUED


def test_terminal_job_refunds_capacity_to_next_in_line():
    client = InMemoryCluster()
    sched = GangScheduler(config=SchedulerConfig(capacity={"v4": (2, 2, 2)}))
    tc = TPUJobController(client, recorder=FakeRecorder(), scheduler=sched)
    winner = submit(client, tpu_job("done-soon"))
    waiter = submit(client, tpu_job("waiter"))
    sync_once(tc, winner)
    sync_once(tc, waiter)
    assert not sched.reconcile_gang(waiter).admitted
    # Drive the winner terminal: both slice pods succeed.
    for pod in client.list(objects.PODS, "default"):
        objects.set_pod_phase(pod, objects.SUCCEEDED)
        objects.set_container_terminated(
            pod, constants.DEFAULT_CONTAINER_NAME, 0
        )
        client.update_status(objects.PODS, pod)
    sync_once(tc, winner)  # records Succeeded
    sync_once(tc, winner)  # terminal path: release_job + cleanup
    assert sched.reconcile_gang(waiter).admitted


def test_admission_aborts_when_annotation_persist_fails():
    """The admitted annotation must land BEFORE any in-memory commit: if
    the persist fails the gang stays queued (and is retried), because an
    admission that exists only in memory would read, after a crash, as a
    queued gang with live pods — which recovery would evict."""
    from tf_operator_tpu.runtime.client import ApiError

    class FlakyCluster(InMemoryCluster):
        fail_job_patches = False

        def patch_merge(self, kind, namespace, name, patch):
            if self.fail_job_patches and kind == objects.TPUJOBS:
                raise ApiError("injected outage")
            return super().patch_merge(kind, namespace, name, patch)

    client = FlakyCluster()
    sched, _ = mk_scheduler(client, capacity={"v4": (2, 2, 2)})
    job = submit(client, tpu_job("flaky"))
    client.fail_job_patches = True
    decision = sched.reconcile_gang(job)
    assert not decision.admitted and decision.state == STATE_QUEUED
    assert sched.snapshot()["chipsInUse"] == {"v4": 0}  # nothing committed
    assert ANNOTATION_STATE not in client.get(
        objects.TPUJOBS, "default", "flaky"
    )["metadata"].get("annotations", {})
    # Outage over: the next pump admits and persists atomically.
    client.fail_job_patches = False
    assert sched.reconcile_gang(job).admitted
    assert client.get(objects.TPUJOBS, "default", "flaky")["metadata"][
        "annotations"][ANNOTATION_STATE] == STATE_ADMITTED


def test_blocked_aged_head_does_not_wedge_preemption_behind_it():
    """An aged low-priority head that can neither place (fleet full) nor
    preempt (no strictly-lower class running) must not block a critical
    gang behind it from preempting — free capacity stays reserved for the
    head, but eviction brings its own."""
    client = InMemoryCluster()
    sched, _ = mk_scheduler(client, capacity={"v4": (2, 2, 2)}, aging=1000.0)
    runner = submit(client, tpu_job("runner", priority_class="high"))
    assert sched.reconcile_gang(runner).admitted

    aged = submit(client, tpu_job("aged", priority_class="low"))
    assert not sched.reconcile_gang(aged).admitted
    # Long wait: with aging 1000 pt/s the low gang's effective priority
    # dwarfs even "critical" — it is unambiguously the queue head.
    sched.queue.get("default/aged").enqueued_at -= 10.0

    crit = submit(client, tpu_job("crit", priority_class="critical"))
    assert sched.reconcile_gang(crit).admitted, (
        "critical must preempt past the blocked aged head"
    )
    snap = sched.snapshot()
    assert [g["key"] for g in snap["admitted"]] == ["default/crit"]
    assert {g["key"] for g in snap["queued"]} == {
        "default/aged", "default/runner"
    }
    # And the aged head really was first in service order.
    assert snap["queued"][0]["key"] == "default/aged"


def test_select_victims_never_evicts_when_free_capacity_suffices():
    placer = TopologyPlacer({"v4": (2, 2, 4)})  # room for two v4-8 blocks
    ledger = QuotaLedger()
    victim = mk_gang("occupant", priority=-100)
    victim.placements = placer.try_fit(victim.slices)
    placer.commit(victim.placements)
    pending = mk_gang("newcomer", priority=100)
    # Half the mesh is still free: no eviction may be proposed.
    assert select_victims(pending, [victim], placer, ledger) is None


def test_gated_pod_rejects_failed_phase_too():
    client = InMemoryCluster()
    sched, _ = mk_scheduler(client)
    job = submit(client, tpu_job("nofail"))
    assert sched.reconcile_gang(job).admitted
    _create_gang_pods(client, job)
    pod = client.list(objects.PODS, "default")[0]
    objects.set_pod_phase(pod, objects.FAILED)
    # A gated pod never ran; accepting Failed would burn restart budget
    # on a slice that never executed an instruction.
    with pytest.raises(Invalid):
        client.update_status(objects.PODS, pod)


def test_infeasible_gang_never_wedges_the_queue():
    """A job that can NEVER fit (generation not in the declared fleet, or
    request over the namespace's whole quota) must not become a permanent
    head-of-line blocker for feasible work behind it."""
    client = InMemoryCluster()
    sched, _ = mk_scheduler(
        client,
        capacity={"v4": (2, 2, 2)},
        quotas={"capped": Quota(chips=4)},
    )
    # Highest priority, but targets a generation this fleet doesn't have.
    ghost = submit(client, tpu_job("ghost", accel="v5e-16",
                                   priority_class="critical"))
    assert not sched.reconcile_gang(ghost).admitted
    # And one whose 8-chip request exceeds its namespace's WHOLE 4-chip
    # quota — infeasible however much capacity frees up.
    glutton = submit(client, tpu_job("glutton", ns="capped"))
    assert not sched.reconcile_gang(glutton).admitted
    # A feasible gang behind both still admits — the pump passes over the
    # infeasible heads instead of stopping at them.
    worker = submit(client, tpu_job("worker"))
    assert sched.reconcile_gang(worker).admitted
    queued = {g["key"]: g for g in sched.snapshot()["queued"]}
    assert set(queued) == {"default/ghost", "capped/glutton"}
    assert all(g.get("infeasible") for g in queued.values())


def test_template_scheduling_gates_survive_gang_gate():
    """A template's own gates (external admission control) ride along with
    the gang gate at creation and SURVIVE the gang release."""
    client = InMemoryCluster()
    tc = TPUJobController(client, recorder=FakeRecorder())
    job = tpu_job("guarded")
    job.spec.replica_specs["Worker"].template["spec"]["schedulingGates"] = [
        {"name": "example.com/budget-approval"}
    ]
    submit(client, job)
    sync_once(tc, job)
    pods = client.list(objects.PODS, "default")
    assert len(pods) == 2
    # Gang gate lifted (unbounded fleet admits same-pass); user gate kept.
    assert all(not is_gated(p) for p in pods)
    assert all(is_gated(p, "example.com/budget-approval") for p in pods)


def test_interrupted_eviction_cleanup_on_queued_gang_with_pods():
    """Crash between the scheduler's state=queued persist and the eviction
    deletion loop: the successor controller finds a QUEUED gang that still
    has pods and finishes the eviction (a queued gang must leave zero
    footprint — its chips are no longer charged in the ledger)."""
    client = InMemoryCluster()
    sched = GangScheduler(config=SchedulerConfig(capacity={"v4": (2, 2, 2)}))
    tc = TPUJobController(client, recorder=FakeRecorder(), scheduler=sched)
    winner = submit(client, tpu_job("winner"))
    sync_once(tc, winner)  # fleet now fully held by the winner

    victim = tpu_job("victim")
    victim.metadata.annotations = {
        ANNOTATION_STATE: STATE_QUEUED,
        ANNOTATION_PREEMPTED_AT: "2026-01-01T00:00:00Z",
    }
    submit(client, victim)
    _create_gang_pods(client, victim, gated=False)  # the half-dead leftovers

    sync_once(tc, victim)
    leftover = [
        p for p in client.list(objects.PODS, "default")
        if p["metadata"]["labels"][constants.LABEL_JOB_NAME] == "victim"
    ]
    assert leftover == []
    ann = client.get(objects.TPUJOBS, "default", "victim")["metadata"][
        "annotations"]
    assert ann[ANNOTATION_STATE] == STATE_QUEUED


def test_release_gang_not_relisted_in_steady_state():
    """Once every pod exists ungated, further syncs must not re-enter
    release_gang (each call is a pod LIST under the scheduler lock)."""
    client = InMemoryCluster()
    tc = TPUJobController(client, recorder=FakeRecorder())
    job = submit(client, tpu_job("steady"))
    sync_once(tc, job)  # creates + releases
    pods = client.list(objects.PODS, "default")
    assert len(pods) == 2 and all(not is_gated(p) for p in pods)

    calls = []
    tc.scheduler.release_gang = lambda j: calls.append(j.key)
    sync_once(tc, job)  # steady state: no gated pods, full set present
    assert calls == []


# ---------------------------------------------------------------------------
# Observability: /debug/scheduler + tpuctl queue + metric families
# ---------------------------------------------------------------------------

def test_debug_scheduler_endpoint_and_tpuctl_queue(capsys):
    from tf_operator_tpu.cli import tpuctl
    from tf_operator_tpu.runtime.apiserver import ApiServer
    from tf_operator_tpu.runtime.observability import mount_observability

    client = InMemoryCluster()
    sched, _ = mk_scheduler(client, capacity={"v4": (2, 2, 2)})
    admitted = submit(client, tpu_job("shown"))
    queued = submit(client, tpu_job("waiting"))
    assert sched.reconcile_gang(admitted).admitted
    assert not sched.reconcile_gang(queued).admitted

    server = ApiServer(client, host="127.0.0.1", port=0)
    mount_observability(server, scheduler=sched)
    server.start()
    try:
        base = f"http://127.0.0.1:{server.server_address[1]}"
        assert tpuctl.main(["--master", base, "queue"]) == 0
        out = capsys.readouterr().out
        assert "default/shown" in out and "default/waiting" in out
        assert "CHIPS-TOTAL" in out
        assert tpuctl.main(["--master", base, "queue", "-o", "json"]) == 0
        snap = json.loads(capsys.readouterr().out)
        assert snap["chipsInUse"] == {"v4": 8}
        assert [g["key"] for g in snap["queued"]] == ["default/waiting"]
    finally:
        server.stop()


def test_scheduler_metric_families_exported():
    from tf_operator_tpu.runtime.metrics import REGISTRY

    rendered = REGISTRY.render()
    for family in (
        "tpu_scheduler_queue_depth",
        "tpu_scheduler_admitted_gangs",
        "tpu_scheduler_admissions_total",
        "tpu_scheduler_preemptions_total",
        "tpu_scheduler_gate_releases_total",
        "tpu_scheduler_admission_latency_seconds",
    ):
        assert family in rendered
