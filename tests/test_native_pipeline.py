"""Native C++ record pipeline vs the Python engine and the shuffle oracle.

The native engine must be deterministic given a seed, batch-for-batch
identical to the Python fallback, and cover every record exactly once per
epoch — so swapping engines can never change training results."""

import numpy as np
import pytest

from tf_operator_tpu.native.pipeline import (
    RecordPipeline,
    epoch_order,
    write_records,
)
from tf_operator_tpu.train.data import record_dataset, write_example_records

RECORDS, REC_BYTES = 23, 8


@pytest.fixture()
def record_file(tmp_path):
    data = np.arange(RECORDS * REC_BYTES, dtype=np.uint8).reshape(
        RECORDS, REC_BYTES
    )
    path = str(tmp_path / "recs.bin")
    write_records(path, data)
    return path, data


def _run(path, engine, **kw):
    defaults = dict(seed=7, shuffle=True, loop=False)
    defaults.update(kw)
    with RecordPipeline(path, REC_BYTES, 4, engine=engine, **defaults) as p:
        return np.concatenate(list(p))


@pytest.mark.parametrize("engine", ["native", "python"])
def test_epoch_covers_every_record_once(record_file, engine):
    path, data = record_file
    rows = _run(path, engine)
    assert rows.shape == data.shape
    assert sorted(rows[:, 0].tolist()) == sorted(data[:, 0].tolist())


def test_native_matches_python_and_oracle(record_file):
    path, data = record_file
    a = _run(path, "native")
    b = _run(path, "native")
    c = _run(path, "python")
    assert np.array_equal(a, b), "native engine nondeterministic"
    assert np.array_equal(a, c), "engines disagree"
    order = epoch_order(RECORDS, 7, 0, True, engine="python")
    assert np.array_equal(a, data[np.asarray(order, np.int64)])


def test_no_shuffle_is_sequential(record_file):
    path, data = record_file
    rows = _run(path, "native", shuffle=False)
    assert np.array_equal(rows, data)


def test_loop_reshuffles_each_epoch(record_file):
    path, data = record_file
    with RecordPipeline(path, REC_BYTES, RECORDS, seed=7, loop=True,
                        engine="native") as p:
        it = iter(p)
        e0, e1 = next(it), next(it)
    assert not np.array_equal(e0, e1)
    assert sorted(e1[:, 0].tolist()) == sorted(data[:, 0].tolist())


def test_auto_engine_prefers_native(record_file):
    path, _ = record_file
    with RecordPipeline(path, REC_BYTES, 4, engine="auto") as p:
        assert p.engine_name == "NativeEngine"


def test_rejects_bad_record_size(tmp_path):
    path = str(tmp_path / "bad.bin")
    with open(path, "wb") as f:
        f.write(b"x" * 13)  # not a multiple of 8
    with pytest.raises(Exception):
        RecordPipeline(path, REC_BYTES, 4, engine="native")


def test_record_dataset_roundtrip(tmp_path):
    feats = np.random.default_rng(0).normal(size=(10, 4, 4)).astype(np.float32)
    labels = np.arange(10, dtype=np.int32)
    path = str(tmp_path / "ds.bin")
    rec = write_example_records(path, feats, labels)
    assert rec == 4 * 4 * 4 + 4

    seen = {}
    it = record_dataset(path, (4, 4), np.float32, 4, seed=1, loop=False)
    for batch in it:
        for img, lab in zip(batch["image"], batch["label"]):
            seen[int(lab)] = img
    assert sorted(seen) == list(range(10))
    for lab, img in seen.items():
        np.testing.assert_array_equal(img, feats[lab])


def test_python_engine_surfaces_producer_errors(tmp_path):
    # A file that shrinks after open: reads past EOF make the producer
    # fail; the consumer must raise, not hang (native-engine parity).
    path = str(tmp_path / "shrink.bin")
    write_records(path, np.zeros((10, REC_BYTES), np.uint8))
    # loop=True + prefetch=1: the producer can pre-read at most two batches
    # before blocking on the queue, so after the truncation below some read
    # of the endless epoch stream MUST fail — no timing window (a non-loop
    # pipeline can prefetch its whole epoch before the truncation lands).
    p = RecordPipeline(
        path, REC_BYTES, 4, engine="python", shuffle=False, loop=True,
        prefetch=1,
    )
    with open(path, "wb") as f:
        f.write(b"x" * REC_BYTES)  # truncate under the reader
    with pytest.raises(IOError):
        for _ in range(20):
            if p._engine.next() is None:
                break
    p.close()


@pytest.mark.parametrize("engine", ["native", "python"])
def test_shards_are_disjoint_and_equal_sized(record_file, engine):
    """Multi-host sharding: with shuffle on, the shards of one epoch are
    disjoint and ALL exactly floor(n/num_shards) records (lockstep hosts;
    the <num_shards remainder is dropped and re-dealt next epoch)."""
    path, data = record_file
    num_shards = 3
    per_shard = [
        _run(path, engine, shard_id=s, num_shards=num_shards)
        for s in range(num_shards)
    ]
    ids = [set(rows[:, 0].tolist()) for rows in per_shard]
    per = RECORDS // num_shards
    assert all(len(rows) == per for rows in per_shard), [
        len(r) for r in per_shard
    ]
    assert len(set().union(*ids)) == per * num_shards  # disjoint
    # Native and python engines deal identical shards.
    other = "python" if engine == "native" else "native"
    np.testing.assert_array_equal(
        per_shard[1], _run(path, other, shard_id=1, num_shards=num_shards)
    )

    # Looping re-deals: epoch 2's shard-0 differs from epoch 1's (shuffle).
    with RecordPipeline(
        path, REC_BYTES, 4, engine=engine, seed=7, shuffle=True, loop=True,
        shard_id=0, num_shards=num_shards,
    ) as p:
        it = iter(p)
        n_epoch = len(per_shard[0])
        epoch1, epoch2 = [], []
        while len(epoch1) < n_epoch:
            epoch1.extend(next(it)[:, 0].tolist())
        while len(epoch2) < n_epoch:
            epoch2.extend(next(it)[:, 0].tolist())
    assert sorted(epoch1) != sorted(epoch2) or epoch1 != epoch2


def test_shard_validation(record_file):
    path, _ = record_file
    with pytest.raises(ValueError):
        RecordPipeline(path, REC_BYTES, 4, shard_id=3, num_shards=3)
    with pytest.raises(ValueError):
        RecordPipeline(path, REC_BYTES, 4, shard_id=0, num_shards=0)


def test_token_dataset_roundtrip_and_next_token_alignment(tmp_path):
    """token_dataset streams LM records through the pipeline: every yielded
    (tokens, targets) pair is the stored sequence split at the next-token
    boundary, each record appears exactly once per epoch."""
    from tf_operator_tpu.train.data import token_dataset, write_token_records

    rng = np.random.default_rng(0)
    seq_len = 8
    seqs = rng.integers(0, 1000, (10, seq_len + 1)).astype(np.int32)
    # Make row identity recoverable: first token = row index.
    seqs[:, 0] = np.arange(10)
    path = str(tmp_path / "toks.bin")
    assert write_token_records(path, seqs) == (seq_len + 1) * 4

    seen = {}
    for batch in token_dataset(path, seq_len, 4, seed=1, loop=False):
        assert batch["tokens"].shape[1] == seq_len
        for toks, targs in zip(batch["tokens"], batch["targets"]):
            row = int(toks[0])
            seen[row] = (toks, targs)
            np.testing.assert_array_equal(toks[1:], targs[:-1])
    assert sorted(seen) == list(range(10))
    for row, (toks, targs) in seen.items():
        np.testing.assert_array_equal(toks, seqs[row, :-1])
        np.testing.assert_array_equal(targs, seqs[row, 1:])

    with np.testing.assert_raises(ValueError):
        write_token_records(path, seqs.reshape(-1))


def test_python_engine_close_unblocks_concurrent_reader(record_file):
    """A reader blocked in next() while close() runs must terminate, even
    when a size-1 prefetch queue refills between close's drain and its
    sentinel put (the producer deposits one final in-flight batch)."""
    import threading

    path, _ = record_file
    p = RecordPipeline(
        path, REC_BYTES, 4, engine="python", seed=1, shuffle=False,
        loop=True, prefetch=1,
    )
    it = iter(p)
    next(it)  # pipeline running, producer refilling the size-1 queue
    results = []

    def reader():
        try:
            while next(it, None) is not None:
                results.append(1)
        except Exception:
            pass

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    p.close()
    t.join(timeout=5)
    assert not t.is_alive(), "reader hung after close()"


# ---------------------------------------------------------------------------
# augment stage (native + numpy engines)
# ---------------------------------------------------------------------------


def test_augment_engines_bit_identical():
    """The C++ and NumPy engines must produce byte-identical output for the
    same (seed, index) stream — same contract as the record pipeline."""
    from tf_operator_tpu.native.augment import augment_batch

    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 256, (16, 40, 40, 3), dtype=np.uint8)
    nat = augment_batch(imgs, (32, 32), seed=7, index0=100, engine="native")
    py = augment_batch(imgs, (32, 32), seed=7, index0=100, engine="python")
    np.testing.assert_array_equal(nat, py)
    # train augmentation actually crops differently across images
    assert not all(
        np.array_equal(nat[i], nat[0]) for i in range(1, 16)
    ) or np.array_equal(imgs[0], imgs[1])


def test_augment_eval_is_center_crop_no_flip():
    from tf_operator_tpu.native.augment import augment_batch

    rng = np.random.default_rng(1)
    imgs = rng.integers(0, 256, (2, 10, 10, 1), dtype=np.uint8)
    out = augment_batch(imgs, (6, 6), train=False, engine="python")
    np.testing.assert_array_equal(out[0], imgs[0, 2:8, 2:8])
    nat = augment_batch(imgs, (6, 6), train=False, engine="native")
    np.testing.assert_array_equal(out, nat)


def test_augment_deterministic_by_seed_and_index():
    from tf_operator_tpu.native.augment import augment_batch

    rng = np.random.default_rng(2)
    imgs = rng.integers(0, 256, (4, 20, 20, 3), dtype=np.uint8)
    a = augment_batch(imgs, (16, 16), seed=3, index0=0)
    b = augment_batch(imgs, (16, 16), seed=3, index0=0)
    np.testing.assert_array_equal(a, b)
    # a different stream position gives different crops (with 5x5x2
    # possible decisions per image, a full collision is ~impossible)
    c = augment_batch(imgs, (16, 16), seed=3, index0=1000)
    assert not np.array_equal(a, c)
    # batch splitting is invisible: [imgs[:2] @ index0=0] + [imgs[2:] @ 2]
    d = np.concatenate([
        augment_batch(imgs[:2], (16, 16), seed=3, index0=0),
        augment_batch(imgs[2:], (16, 16), seed=3, index0=2),
    ])
    np.testing.assert_array_equal(a, d)


def test_augment_rejects_bad_inputs():
    from tf_operator_tpu.native.augment import augment_batch

    with pytest.raises(ValueError):
        augment_batch(np.zeros((2, 8, 8, 3), np.float32), (4, 4))
    with pytest.raises(ValueError):
        augment_batch(np.zeros((2, 8, 8, 3), np.uint8), (16, 4))


def test_record_dataset_with_crop(tmp_path):
    """record_dataset(crop_hw=...) runs the augment stage inline: uint8
    records stored at 12x12 come out center-cropped to 8x8 in eval mode."""
    rng = np.random.default_rng(3)
    feats = rng.integers(0, 256, (6, 12, 12, 3), dtype=np.uint8)
    labels = np.arange(6, dtype=np.int32)
    path = str(tmp_path / "crop.bin")
    write_example_records(path, feats, labels)

    it = record_dataset(
        path, (12, 12, 3), np.uint8, 3, seed=1, shuffle=False, loop=False,
        crop_hw=(8, 8), augment_train=False,
    )
    got = {int(l): img for b in it for img, l in zip(b["image"], b["label"])}
    assert got[0].shape == (8, 8, 3)
    np.testing.assert_array_equal(got[0], feats[0, 2:10, 2:10])

    # misconfiguration raises at the call site, not on first next()
    with pytest.raises(ValueError):
        record_dataset(path, (12, 12, 3), np.float32, 3, crop_hw=(8, 8))


class TestAugmentRecords:
    def test_records_path_matches_batch_path(self):
        """augment_records (strided, zero-copy glue) must be bit-identical
        to augment_batch over the sliced-and-reshaped image batch, for both
        engines."""
        import numpy as np

        from tf_operator_tpu.native.augment import augment_batch, augment_records

        rng = np.random.default_rng(3)
        n, rs, os_ = 16, 40, 32
        rec_bytes = rs * rs * 3 + 1
        records = rng.integers(0, 256, (n, rec_bytes), np.uint8)
        images = records[:, :-1].reshape(n, rs, rs, 3)

        for engine in ("native", "python"):
            try:
                via_batch = augment_batch(
                    images, (os_, os_), seed=9, index0=7, engine=engine
                )
                via_records = augment_records(
                    records, (rs, rs, 3), (os_, os_), seed=9, index0=7,
                    engine=engine,
                )
            except Exception as e:  # native engine may be unavailable
                if engine == "native":
                    import pytest as _pytest

                    _pytest.skip(f"native engine unavailable: {e}")
                raise
            assert (via_batch == via_records).all(), engine

    def test_out_param_writes_in_place(self):
        import numpy as np
        import pytest as _pytest

        from tf_operator_tpu.native.augment import augment_records

        rng = np.random.default_rng(4)
        n, rs, os_ = 4, 24, 16
        records = rng.integers(0, 256, (n, rs * rs * 3 + 1), np.uint8)
        stacked = np.zeros((2, n, os_, os_, 3), np.uint8)
        got = augment_records(
            records, (rs, rs, 3), (os_, os_), seed=1, out=stacked[1]
        )
        assert got.base is stacked or got is stacked[1] or (
            got.__array_interface__["data"][0]
            == stacked[1].__array_interface__["data"][0]
        )
        assert stacked[1].any() and not stacked[0].any()

        with _pytest.raises(ValueError, match="out must be"):
            augment_records(
                records, (rs, rs, 3), (os_, os_),
                out=np.zeros((n, os_, os_, 3), np.int32),
            )


class TestMMapRecordPipeline:
    def test_same_sample_stream_as_record_pipeline(self, tmp_path):
        """Swapping pipelines must not change the sample stream: the mmap
        pipeline's index batches, gathered, equal RecordPipeline's record
        batches for the same (seed, shuffle, shard) config."""
        import numpy as np

        from tf_operator_tpu.native.pipeline import (
            MMapRecordPipeline,
            RecordPipeline,
            write_records,
        )

        rng = np.random.default_rng(5)
        rec_bytes, n = 17, 23
        path = str(tmp_path / "recs.bin")
        write_records(path, rng.integers(0, 256, (n, rec_bytes), np.uint8))
        table = np.fromfile(path, np.uint8).reshape(n, rec_bytes)

        for shard_id, num_shards in ((0, 1), (1, 2)):
            mp = MMapRecordPipeline(
                path, rec_bytes, batch=4, seed=3, shuffle=True,
                shard_id=shard_id, num_shards=num_shards,
            )
            rp = RecordPipeline(
                path, rec_bytes, batch=4, seed=3, shuffle=True,
                shard_id=shard_id, num_shards=num_shards,
            )
            it = iter(rp)
            while True:
                idx = mp.next_indices()
                if idx is None:
                    assert next(it, None) is None
                    break
                got = next(it)
                assert (table[idx] == got).all()
            rp.close()

    def test_labels_and_loop(self, tmp_path):
        import numpy as np

        from tf_operator_tpu.native.pipeline import (
            MMapRecordPipeline,
            write_records,
        )

        rec_bytes, n = 8, 6
        recs = np.zeros((n, rec_bytes), np.uint8)
        recs[:, -1] = np.arange(n)
        path = str(tmp_path / "l.bin")
        write_records(path, recs)
        mp = MMapRecordPipeline(
            path, rec_bytes, batch=4, shuffle=False, loop=True
        )
        idx = mp.next_indices()
        assert (mp.labels(idx) == idx.astype(np.int32)).all()
        # loop=True rolls epochs forever.
        for _ in range(5):
            assert mp.next_indices() is not None


class TestEpochOrderNative:
    def test_native_matches_python_oracle(self):
        """dp_epoch_order must be bit-identical to the Python Fisher-Yates
        across seeds/epochs/shards (it is the same splitmix64 stream).
        Skips when the native library is unavailable — otherwise auto
        falls back to Python and the comparison is vacuous."""
        import numpy as np

        from tf_operator_tpu.native.pipeline import (
            _native_epoch_order,
            epoch_order,
        )

        if _native_epoch_order(8, 0, 0, True, 0, 1) is None:
            import pytest as _pytest

            _pytest.skip("native engine unavailable")
        for n, seed, epoch, shuffle, shard in [
            (1, 0, 0, True, (0, 1)),
            (97, 3, 0, True, (0, 1)),
            (97, 3, 5, True, (1, 3)),
            (256, 11, 2, False, (2, 4)),
            (1000, 42, 1, True, (0, 2)),
        ]:
            py = epoch_order(n, seed, epoch, shuffle, *shard, engine="python")
            auto = epoch_order(n, seed, epoch, shuffle, *shard)
            assert np.array_equal(py, auto), (n, seed, epoch, shuffle, shard)

    def test_large_order_is_fast(self):
        """The native path must handle million-record epochs in well under
        a second (the Python loop takes tens of seconds there)."""
        import time

        from tf_operator_tpu.native.pipeline import _native_epoch_order

        if _native_epoch_order(8, 0, 0, True, 0, 1) is None:
            import pytest as _pytest

            _pytest.skip("native engine unavailable")
        t0 = time.perf_counter()
        out = _native_epoch_order(1_000_000, 7, 0, True, 0, 1)
        dt = time.perf_counter() - t0
        assert out is not None and len(out) == 1_000_000
        assert dt < 2.0, f"native epoch order took {dt:.2f}s"


def test_record_dataset_mmap_engine_matches_default(tmp_path):
    """engine="mmap" must yield the bit-identical sample stream to the
    default pipeline — same epoch order, same crops/flips, same labels —
    for both cropped and uncropped datasets."""
    import numpy as np

    from tf_operator_tpu.train.data import record_dataset, write_example_records

    rng = np.random.default_rng(8)
    imgs = rng.integers(0, 256, (22, 12, 12, 3), np.uint8)
    labels = rng.integers(0, 10, (22,)).astype(np.int32)
    path = str(tmp_path / "m.bin")
    write_example_records(path, imgs, labels)

    for crop in (None, (8, 8)):
        a = list(record_dataset(
            path, (12, 12, 3), np.uint8, 5, seed=4, loop=False,
            crop_hw=crop,
        ))
        b = list(record_dataset(
            path, (12, 12, 3), np.uint8, 5, seed=4, loop=False,
            crop_hw=crop, engine="mmap",
        ))
        assert len(a) == len(b) > 0, crop
        for x, y in zip(a, b):
            assert (x["image"] == y["image"]).all(), crop
            assert (x["label"] == y["label"]).all(), crop
