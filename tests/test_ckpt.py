"""Checkpoint-coordination unit + integration tests (ckpt/protocol.py,
ckpt/registry.py, ckpt/gc.py, the controller roll-up, resume injection,
and the local executor's ack relay / signal delivery with real processes).

The eviction-barrier chaos cases (crash boundaries on both cluster
backends) live in tests/test_ckpt_chaos.py.
"""

import json
import os
import sys
import threading
import time

import pytest

from tf_operator_tpu.api import constants
from tf_operator_tpu.ckpt import protocol
from tf_operator_tpu.ckpt.gc import CheckpointSweeper, SweepConfig
from tf_operator_tpu.ckpt.registry import CheckpointRegistry, CkptConfig
from tf_operator_tpu.controller.jobcontroller import JobControllerConfig
from tf_operator_tpu.controller.tpujob_controller import TPUJobController
from tf_operator_tpu.runtime import objects
from tf_operator_tpu.runtime.events import FakeRecorder
from tf_operator_tpu.runtime.memcluster import InMemoryCluster
from tf_operator_tpu.runtime.metrics import (
    CKPT_ACKS_TOTAL,
    CKPT_GC_STEPS_TOTAL,
    CKPT_RESUME_INJECTIONS_TOTAL,
)
from tf_operator_tpu.scheduler import GangScheduler, SchedulerConfig

pytestmark = pytest.mark.ckpt


# ---------------------------------------------------------------------------
# protocol
# ---------------------------------------------------------------------------


def test_ack_file_roundtrip(tmp_path):
    path = str(tmp_path / "ack.json")
    assert protocol.read_ack(path) is None
    protocol.write_ack(path, 42, "/ckpt/demo")
    ack = protocol.read_ack(path)
    assert ack is not None
    assert ack.step == 42 and ack.directory == "/ckpt/demo"
    assert ack.saved_at.endswith("Z")
    # Overwrite advances; no partial files linger.
    protocol.write_ack(path, 43)
    assert protocol.read_ack(path).step == 43
    assert [f for f in os.listdir(tmp_path) if "tmp" in f] == []


def test_signal_gen_monotone_and_deadline_roundtrip():
    from tf_operator_tpu.utils.times import parse_rfc3339

    g1 = protocol.new_signal_gen(1000.0)
    g2 = protocol.new_signal_gen(1000.5)
    assert g2 > g1
    # Sub-second deadlines round-trip through the annotation format.
    epoch = 1_700_000_000.25
    assert abs(parse_rfc3339(protocol.fmt_deadline(epoch)) - epoch) < 1e-3


def test_all_pods_acked():
    def pod(ack=None):
        p = {"metadata": {"annotations": {}}}
        if ack is not None:
            p["metadata"]["annotations"][protocol.POD_ACK] = str(ack)
        return p

    assert not protocol.all_pods_acked([], 5)
    assert not protocol.all_pods_acked([pod(5), pod()], 5)
    assert not protocol.all_pods_acked([pod(4)], 5)
    assert protocol.all_pods_acked([pod(5), pod(9)], 5)


# ---------------------------------------------------------------------------
# registry roll-up
# ---------------------------------------------------------------------------


def ckpt_job(name="train", replicas=2):
    return {
        "apiVersion": constants.API_VERSION,
        "kind": constants.KIND,
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "replicaSpecs": {
                "Worker": {
                    "replicas": replicas,
                    "template": {
                        "spec": {
                            "containers": [
                                {
                                    "name": constants.DEFAULT_CONTAINER_NAME,
                                    "image": "x",
                                    "command": ["unused"],
                                }
                            ]
                        }
                    },
                }
            }
        },
    }


def mk_controller(client, grace=0.0, stale_after=600.0):
    sched = GangScheduler(config=SchedulerConfig(checkpoint_grace=grace))
    registry = CheckpointRegistry(
        sched, config=CkptConfig(stale_after=stale_after)
    )
    tc = TPUJobController(
        client,
        JobControllerConfig(reconcile_period=0.2),
        recorder=FakeRecorder(),
        scheduler=sched,
    )
    assert tc.ckpt is registry  # the flag-configured registry won
    return sched, registry, tc


def sync(tc, key):
    tc.job_informer.sync_now()
    tc.pod_informer.sync_now()
    tc.service_informer.sync_now()
    return tc.sync_job(key)


def stamp_pod(client, name, step, ack=None, directory="/ckpt/train"):
    ann = {
        protocol.POD_STEP: str(step),
        protocol.POD_SAVED_AT: objects.now_iso(),
        protocol.POD_DIR: directory,
    }
    if ack is not None:
        ann[protocol.POD_ACK] = str(ack)
    client.patch_merge(
        objects.PODS, "default", name, {"metadata": {"annotations": ann}}
    )


def job_ann(client, name="train"):
    return client.get(objects.TPUJOBS, "default", name)["metadata"].get(
        "annotations", {}
    )


def test_rollup_is_min_over_reporters_and_monotone():
    client = InMemoryCluster()
    _, registry, tc = mk_controller(client)
    client.create(objects.TPUJOBS, ckpt_job())
    sync(tc, "default/train")  # creates the two worker pods
    acks_before = CKPT_ACKS_TOTAL.value()

    # Only worker 0 reports: the roll-up records its step.
    stamp_pod(client, "train-worker-0", 10)
    sync(tc, "default/train")
    ann = job_ann(client)
    assert ann[protocol.JOB_STEP] == "10"
    assert ann[protocol.JOB_DIR] == "/ckpt/train"
    assert ann[protocol.JOB_ACKED_AT]
    assert CKPT_ACKS_TOTAL.value() == acks_before + 1

    # Both report: min over reporters.
    stamp_pod(client, "train-worker-0", 30)
    stamp_pod(client, "train-worker-1", 20)
    sync(tc, "default/train")
    assert job_ann(client)[protocol.JOB_STEP] == "20"

    # A lower report never regresses the record.
    stamp_pod(client, "train-worker-1", 15)
    sync(tc, "default/train")
    assert job_ann(client)[protocol.JOB_STEP] == "20"

    # Status mirrors the annotation record.
    job = client.get(objects.TPUJOBS, "default", "train")
    assert job["status"]["lastCheckpointStep"] == 20
    rec = registry.record_of("default/train")
    assert rec.latest_step == 20 and rec.directory == "/ckpt/train"


def test_rollup_noop_for_non_checkpointing_jobs():
    """A job whose pods never report must see zero checkpoint artifacts:
    no annotations, no status field, no conditions."""
    client = InMemoryCluster()
    _, _, tc = mk_controller(client)
    client.create(objects.TPUJOBS, ckpt_job("plain"))
    for _ in range(3):
        sync(tc, "default/plain")
    ann = job_ann(client, "plain")
    assert not any(k.startswith("ckpt.") for k in ann)
    job = client.get(objects.TPUJOBS, "default", "plain")
    assert "lastCheckpointStep" not in job["status"]
    types = {c["type"] for c in job["status"].get("conditions", [])}
    assert "CheckpointStale" not in types
    assert "CheckpointSkipped" not in types


def test_registry_recovers_record_from_annotations():
    """A successor controller (fresh registry) rebuilds the record from
    the persisted job annotations on its first sync — crash discipline."""
    client = InMemoryCluster()
    _, _, tc1 = mk_controller(client)
    client.create(objects.TPUJOBS, ckpt_job())
    sync(tc1, "default/train")
    stamp_pod(client, "train-worker-0", 7)
    stamp_pod(client, "train-worker-1", 7)
    sync(tc1, "default/train")
    assert job_ann(client)[protocol.JOB_STEP] == "7"

    _, registry2, tc2 = mk_controller(client)
    sync(tc2, "default/train")
    rec = registry2.record_of("default/train")
    assert rec is not None and rec.latest_step == 7
    job = client.get(objects.TPUJOBS, "default", "train")
    assert job["status"]["lastCheckpointStep"] == 7


def test_resume_env_injected_into_replacement_pods():
    client = InMemoryCluster()
    _, _, tc = mk_controller(client)
    client.create(objects.TPUJOBS, ckpt_job())
    sync(tc, "default/train")
    stamp_pod(client, "train-worker-0", 12)
    stamp_pod(client, "train-worker-1", 12)
    sync(tc, "default/train")

    injections_before = CKPT_RESUME_INJECTIONS_TOTAL.value()
    # Delete a pod; the recreated one carries the resume contract.
    client.delete(objects.PODS, "default", "train-worker-0")
    sync(tc, "default/train")
    pod = client.get(objects.PODS, "default", "train-worker-0")
    env = {
        e["name"]: e.get("value")
        for c in pod["spec"]["containers"]
        if c["name"] == constants.DEFAULT_CONTAINER_NAME
        for e in c.get("env", [])
    }
    assert env[protocol.ENV_RESUME_STEP] == "12"
    assert env[protocol.ENV_CKPT_DIR] == "/ckpt/train"
    assert CKPT_RESUME_INJECTIONS_TOTAL.value() > injections_before


def test_stale_condition_flips_and_recovers():
    # stale_after must exceed the 1s rounding of the acked-at stamp.
    client = InMemoryCluster()
    _, _, tc = mk_controller(client, stale_after=1.5)
    client.create(objects.TPUJOBS, ckpt_job(replicas=1))
    sync(tc, "default/train")
    stamp_pod(client, "train-worker-0", 5)
    # Run the pod so the job gets the Running condition staleness needs.
    pod = client.get(objects.PODS, "default", "train-worker-0")
    objects.set_pod_phase(pod, objects.RUNNING)
    client.update_status(objects.PODS, pod)
    sync(tc, "default/train")
    job = client.get(objects.TPUJOBS, "default", "train")
    conds = {c["type"]: c["status"] for c in job["status"]["conditions"]}
    assert conds.get("CheckpointStale") is None  # fresh ack, not stale

    time.sleep(1.7)
    sync(tc, "default/train")
    job = client.get(objects.TPUJOBS, "default", "train")
    conds = {c["type"]: c["status"] for c in job["status"]["conditions"]}
    assert conds["CheckpointStale"] == "True"

    # A new durable save flips it back.
    stamp_pod(client, "train-worker-0", 6)
    sync(tc, "default/train")
    job = client.get(objects.TPUJOBS, "default", "train")
    conds = {c["type"]: c["status"] for c in job["status"]["conditions"]}
    assert conds["CheckpointStale"] == "False"


# ---------------------------------------------------------------------------
# retention sweeper
# ---------------------------------------------------------------------------


def _mk_steps(root, steps):
    for s in steps:
        d = root / str(s)
        d.mkdir(parents=True)
        (d / "data").write_text("x")


def _succeed(client, name):
    job = client.get(objects.TPUJOBS, "default", name)
    job.setdefault("status", {})["conditions"] = [
        {"type": "Succeeded", "status": "True"}
    ]
    client.update_status(objects.TPUJOBS, job)


def test_sweeper_prunes_succeeded_jobs_only(tmp_path):
    client = InMemoryCluster()
    done_dir = tmp_path / "done"
    live_dir = tmp_path / "live"
    _mk_steps(done_dir, [1, 3, 5, 7])
    _mk_steps(live_dir, [2, 4])

    for name, d in (("done", done_dir), ("live", live_dir)):
        job = ckpt_job(name)
        job["metadata"]["annotations"] = {protocol.JOB_DIR: str(d)}
        client.create(objects.TPUJOBS, job)
    _succeed(client, "done")

    gc_before = CKPT_GC_STEPS_TOTAL.value()
    sweeper = CheckpointSweeper(client, SweepConfig(keep=1))
    removed = sweeper.sweep()
    assert removed == 3
    assert sorted(os.listdir(done_dir)) == ["7"]  # newest kept
    assert sorted(os.listdir(live_dir)) == ["2", "4"]  # running: untouched
    assert CKPT_GC_STEPS_TOTAL.value() == gc_before + 3
    # Idempotent.
    assert sweeper.sweep() == 0


def test_sweeper_ttl_expires_even_the_newest(tmp_path):
    client = InMemoryCluster()
    d = tmp_path / "old"
    _mk_steps(d, [9])
    os.utime(d / "9", (time.time() - 100, time.time() - 100))
    job = ckpt_job("old")
    job["metadata"]["annotations"] = {protocol.JOB_DIR: str(d)}
    client.create(objects.TPUJOBS, job)
    _succeed(client, "old")

    keeper = CheckpointSweeper(client, SweepConfig(keep=1, ttl=0.0))
    assert keeper.sweep() == 0  # no TTL: newest survives
    expirer = CheckpointSweeper(client, SweepConfig(keep=1, ttl=50.0))
    assert expirer.sweep() == 1
    assert os.listdir(d) == []
    assert d.exists()  # the root itself is never removed


def test_sweeper_ignores_non_step_entries(tmp_path):
    client = InMemoryCluster()
    d = tmp_path / "mixed"
    _mk_steps(d, [1, 2])
    (d / "not-a-step").mkdir()
    (d / "3").write_text("a FILE named like a step")
    job = ckpt_job("mixed")
    job["metadata"]["annotations"] = {protocol.JOB_DIR: str(d)}
    client.create(objects.TPUJOBS, job)
    _succeed(client, "mixed")
    CheckpointSweeper(client, SweepConfig(keep=1)).sweep()
    assert sorted(os.listdir(d)) == ["2", "3", "not-a-step"]


# ---------------------------------------------------------------------------
# local executor: ack relay + signal delivery (real processes)
# ---------------------------------------------------------------------------

WORKLOAD = r"""
import json, os, signal, sys, time

ack_path = os.environ["TPU_CKPT_ACK_FILE"]
step = 0
signaled = {"v": False}

def write(s):
    tmp = ack_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"step": s, "dir": "/ckpt/proc",
                   "savedAt": "2026-01-01T00:00:00Z"}, f)
    os.replace(tmp, ack_path)

signal.signal(signal.SIGTERM, lambda *_: signaled.__setitem__("v", True))
write(step)
deadline = time.time() + 30
while time.time() < deadline:
    time.sleep(0.05)
    step += 1
    if step % 4 == 0:
        write(step)
    if signaled["v"]:
        write(step)  # the forced eviction save
        signaled["v"] = False
"""


def test_executor_relays_acks_and_delivers_signal(tmp_path):
    from tf_operator_tpu.runtime.executor import LocalProcessExecutor

    script = tmp_path / "workload.py"
    script.write_text(WORKLOAD)
    client = InMemoryCluster()
    executor = LocalProcessExecutor(client, "default")
    stop = threading.Event()
    executor.start(stop)
    try:
        pod = objects.new_pod(
            "ckpt-proc-0",
            containers=[
                {
                    "name": constants.DEFAULT_CONTAINER_NAME,
                    "command": [sys.executable, str(script)],
                }
            ],
        )
        client.create(objects.PODS, pod)

        def ann_of():
            return client.get(objects.PODS, "default", "ckpt-proc-0")[
                "metadata"
            ].get("annotations", {})

        # 1. Periodic acks surface as pod annotations (step + dir).
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if protocol.POD_STEP in ann_of():
                break
            time.sleep(0.05)
        ann = ann_of()
        assert protocol.POD_STEP in ann, "ack relay never reported a step"
        assert ann[protocol.POD_DIR] == "/ckpt/proc"
        assert protocol.POD_ACK not in ann  # no signal yet → no ack

        # 2. The eviction signal annotation is delivered as SIGTERM; the
        #    workload's post-signal save becomes the barrier ack.
        gen = protocol.new_signal_gen()
        client.patch_merge(
            objects.PODS, "default", "ckpt-proc-0",
            {"metadata": {"annotations": {protocol.POD_SIGNAL: str(gen)}}},
        )
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if ann_of().get(protocol.POD_ACK) == str(gen):
                break
            time.sleep(0.05)
        assert ann_of().get(protocol.POD_ACK) == str(gen)
        # The process is still alive: the signal requests a checkpoint,
        # it does not kill the pod (the barrier decides when to evict).
        assert objects.pod_phase(
            client.get(objects.PODS, "default", "ckpt-proc-0")
        ) == objects.RUNNING
    finally:
        stop.set()
        time.sleep(0.3)


# ---------------------------------------------------------------------------
# CheckpointManager: ack writing + the follower reload fix
# ---------------------------------------------------------------------------


def test_manager_ack_and_follower_min_step(tmp_path):
    """restore_or_init(min_step=...) must reload() a stale step cache: a
    manager opened before another process wrote steps resumes from the
    operator's acked step, not from its cached (empty/old) view. Also
    pins ack()/maybe_ack() writing the ack file protocol."""
    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.mnist import MnistCNN
    from tf_operator_tpu.parallel.mesh import create_mesh
    from tf_operator_tpu.parallel.sharding import replicate
    from tf_operator_tpu.train.checkpoint import CheckpointManager
    from tf_operator_tpu.train.steps import TrainState, sgd_momentum

    mesh = create_mesh({"dp": 8})
    model = MnistCNN()
    x = jnp.zeros((4, 28, 28, 1), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=True)
    state = replicate(
        mesh, TrainState.create(variables["params"], sgd_momentum(0.1))
    )
    path = str(tmp_path / "ckpt")
    ack_path = str(tmp_path / "ack.json")

    # The follower opens the (empty) directory FIRST and caches the view.
    follower = CheckpointManager(path)
    assert follower.latest_step() is None

    # The writer (the evicted predecessor) saves step 5 and acks it.
    with CheckpointManager(path, ack_path=ack_path) as writer:
        writer.save(5, state)
        acked = writer.ack()
    assert acked == 5
    ack = protocol.read_ack(ack_path)
    assert ack.step == 5 and ack.directory == os.path.abspath(path)

    # Without min_step the follower's cache can miss the write; with the
    # operator's contract it reloads and resumes AFTER the acked step.
    _, start = follower.restore_or_init(state, min_step=5)
    assert start == 6
    follower.close()


def test_manager_maybe_ack_reports_committed_steps(tmp_path):
    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.mnist import MnistCNN
    from tf_operator_tpu.parallel.mesh import create_mesh
    from tf_operator_tpu.parallel.sharding import replicate
    from tf_operator_tpu.train.checkpoint import CheckpointManager
    from tf_operator_tpu.train.steps import TrainState, sgd_momentum

    mesh = create_mesh({"dp": 8})
    model = MnistCNN()
    x = jnp.zeros((4, 28, 28, 1), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=True)
    state = replicate(
        mesh, TrainState.create(variables["params"], sgd_momentum(0.1))
    )
    ack_path = str(tmp_path / "ack.json")
    with CheckpointManager(
        str(tmp_path / "c"), ack_path=ack_path
    ) as mgr:
        assert mgr.maybe_ack() is None  # nothing committed yet
        mgr.save(3, state)
        mgr.wait()
        assert mgr.maybe_ack() == 3
        assert mgr.maybe_ack() is None  # deduped: unchanged step
    assert protocol.read_ack(ack_path).step == 3


def test_workload_env_helpers(monkeypatch):
    from tf_operator_tpu.train import checkpoint as ckpt_lib

    monkeypatch.delenv(protocol.ENV_RESUME_STEP, raising=False)
    monkeypatch.delenv(protocol.ENV_CKPT_DIR, raising=False)
    assert ckpt_lib.resume_min_step() is None
    assert ckpt_lib.injected_dir() is None
    monkeypatch.setenv(protocol.ENV_RESUME_STEP, "17")
    monkeypatch.setenv(protocol.ENV_CKPT_DIR, "/ckpt/x")
    assert ckpt_lib.resume_min_step() == 17
    assert ckpt_lib.injected_dir() == "/ckpt/x"
    monkeypatch.setenv(protocol.ENV_RESUME_STEP, "junk")
    assert ckpt_lib.resume_min_step() is None


# ---------------------------------------------------------------------------
# /debug/ckpt snapshot shape
# ---------------------------------------------------------------------------


def test_registry_snapshot_shape():
    client = InMemoryCluster()
    _, registry, tc = mk_controller(client)
    client.create(objects.TPUJOBS, ckpt_job())
    sync(tc, "default/train")
    stamp_pod(client, "train-worker-0", 4)
    stamp_pod(client, "train-worker-1", 4)
    sync(tc, "default/train")
    snap = json.loads(json.dumps(registry.snapshot()))  # JSON-serializable
    jobs = {j["key"]: j for j in snap["jobs"]}
    rec = jobs["default/train"]
    assert rec["latestStep"] == 4
    assert rec["reportingPods"] == 2
    assert snap["config"]["staleAfter"] == 600.0
