"""Leader-election tests: single-winner guarantee under racing candidates,
failover on expiry, and clean release (reference server.go:140-152 analog,
CAS-on-resourceVersion instead of an Endpoints lock)."""

import threading
import time

from tf_operator_tpu.runtime.leader_election import (
    LeaderElectionConfig,
    LeaderElector,
)
from tf_operator_tpu.runtime.memcluster import InMemoryCluster


def make_elector(client, ident, cfg, log):
    def on_start(leading_stop):
        log.append(("start", ident))
        leading_stop.wait()

    def on_stop():
        log.append(("stop", ident))

    return LeaderElector(client, ident, on_start, on_stop, cfg)


def test_single_winner():
    client = InMemoryCluster()
    cfg = LeaderElectionConfig(lease_duration=2.0, renew_deadline=0.1, retry_period=0.1)
    log = []
    stops = [threading.Event() for _ in range(3)]
    electors = [make_elector(client, f"cand-{i}", cfg, log) for i in range(3)]
    threads = [
        threading.Thread(target=e.run, args=(s,), daemon=True)
        for e, s in zip(electors, stops)
    ]
    for t in threads:
        t.start()
    time.sleep(1.0)
    leaders = [e for e in electors if e.is_leader.is_set()]
    assert len(leaders) == 1
    for s in stops:
        s.set()
    for t in threads:
        t.join(timeout=2)


def test_failover_on_expiry():
    client = InMemoryCluster()
    cfg = LeaderElectionConfig(lease_duration=0.5, renew_deadline=0.1, retry_period=0.1)
    log = []

    stop_a = threading.Event()
    a = make_elector(client, "a", cfg, log)
    ta = threading.Thread(target=a.run, args=(stop_a,), daemon=True)
    ta.start()
    assert a.is_leader.wait(timeout=3)

    # Kill A without release (simulated crash: stop its loop but don't call
    # release) — B must take over after the lease expires.
    stop_a.set()
    ta.join(timeout=2)
    # Undo the graceful release the loop performed: restore a live-looking
    # lease owned by the dead candidate to simulate a crash.
    lease = client.get("leases", cfg.namespace, cfg.lease_name)
    lease["spec"]["holderIdentity"] = "a"
    lease["spec"]["renewTime"] = time.time()
    client.update("leases", lease)

    stop_b = threading.Event()
    b = make_elector(client, "b", cfg, log)
    tb = threading.Thread(target=b.run, args=(stop_b,), daemon=True)
    tb.start()
    # Not immediately: the (fake) live lease blocks B…
    time.sleep(0.2)
    assert not b.is_leader.is_set()
    # …until it expires.
    assert b.is_leader.wait(timeout=3)
    stop_b.set()
    tb.join(timeout=2)


def test_release_hands_off_quickly():
    client = InMemoryCluster()
    cfg = LeaderElectionConfig(lease_duration=30.0, renew_deadline=0.1, retry_period=0.1)
    log = []
    stop_a = threading.Event()
    a = make_elector(client, "a", cfg, log)
    ta = threading.Thread(target=a.run, args=(stop_a,), daemon=True)
    ta.start()
    assert a.is_leader.wait(timeout=3)
    stop_a.set()  # graceful: release() zeroes renewTime
    ta.join(timeout=2)

    stop_b = threading.Event()
    b = make_elector(client, "b", cfg, log)
    tb = threading.Thread(target=b.run, args=(stop_b,), daemon=True)
    tb.start()
    # Despite the 30s lease, release lets B in immediately.
    assert b.is_leader.wait(timeout=3)
    stop_b.set()
    tb.join(timeout=2)
    assert ("start", "a") in log and ("start", "b") in log
