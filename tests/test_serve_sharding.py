"""Fast unit tests for the engine-state sharding layer
(tf_operator_tpu/serve/sharding.py): the mesh LAYOUT as data — which
leaf gets which PartitionSpec, the can't-tile fallback, and the debug
shape — all computable without touching a device (the multi-device
bit-identity matrix lives in tests/test_serve_tp.py, slow-marked,
because it needs a >1-device process)."""

import numpy as np
import pytest

from jax.sharding import PartitionSpec as P

from tf_operator_tpu.serve.sharding import (
    cache_specs,
    dp_size_of,
    leaf_spec,
    logits_spec,
    mesh_debug,
    shard_block_extent,
    shard_of_slot,
    ship_specs,
    slot_spec,
    tp_size_of,
)

pytestmark = pytest.mark.serve


def arr(*shape):
    return np.zeros(shape, np.float32)


class TestLeafSpec:
    def test_paged_pool_sharded_on_kv_head_axis(self):
        # [nb, blk, KV, Dh]: the KV axis is dim 2.
        assert leaf_spec("pool_key", (25, 8, 4, 16), 2) == \
            P(None, None, "tp", None)
        assert leaf_spec("pool_value", (25, 8, 4, 16), 2) == \
            P(None, None, "tp", None)

    def test_dense_rows_sharded_on_kv_head_axis(self):
        # Stacked [slots, 1, S, KV, Dh] and solo [1, S, KV, Dh]: the
        # suffix addressing finds KV at -2 in both.
        assert leaf_spec("cached_key", (3, 1, 64, 4, 16), 2) == \
            P(None, None, None, "tp", None)
        assert leaf_spec("cached_value", (1, 64, 4, 16), 2) == \
            P(None, None, "tp", None)

    def test_kv8_scale_sidecars_ride_the_head_shard(self):
        # [slots, 1, S, KV]: KV is the LAST axis for the scale leaves.
        assert leaf_spec("key_scale", (3, 1, 64, 4), 2) == \
            P(None, None, None, "tp")
        assert leaf_spec("value_scale", (1, 64, 4), 2) == \
            P(None, None, "tp")

    def test_per_slot_state_replicates(self):
        for name in ("block_table", "cache_index", "pos_index"):
            assert leaf_spec(name, (3, 8), 2) == P()

    def test_untileable_heads_fall_back_replicated(self):
        # KV=3 heads over tp=2: placement is an optimization, never a
        # correctness requirement — replicate rather than crash.
        assert leaf_spec("pool_key", (25, 8, 3, 16), 2) == P()

    def test_tp1_replicates_everything(self):
        assert leaf_spec("pool_key", (25, 8, 4, 16), 1) == P()


class TestCacheSpecs:
    def test_walks_nested_tree_and_mirrors_shape(self):
        tree = {
            "block_0": {
                "attn": {
                    "pool_key": arr(25, 8, 4, 16),
                    "pool_value": arr(25, 8, 4, 16),
                    "block_table": arr(3, 8),
                    "cache_index": arr(3),
                },
            },
            "pos_index": arr(3),
        }
        specs = cache_specs(tree, 2)
        attn = specs["block_0"]["attn"]
        assert attn["pool_key"] == P(None, None, "tp", None)
        assert attn["pool_value"] == P(None, None, "tp", None)
        assert attn["block_table"] == P()
        assert attn["cache_index"] == P()
        assert specs["pos_index"] == P()

    def test_custom_axis_name(self):
        tree = {"pool_key": arr(25, 8, 4, 16)}
        assert cache_specs(tree, 4, tp_axis="model")["pool_key"] == \
            P(None, None, "model", None)


class TestLogitsSpec:
    def test_vocab_split_matches_lm_head(self):
        assert logits_spec((8, 64), 2) == P(None, "tp")

    def test_odd_vocab_replicates(self):
        assert logits_spec((8, 63), 2) == P()

    def test_tp1_replicates(self):
        assert logits_spec((8, 64), 1) == P()


class TestDpAxis:
    """The ``dp`` mesh axis over slots (PR 10 follow-on, ISSUE 14):
    per-slot leaves shard their leading slot axis, the shared paged
    pool replicates over dp — specs as pure data. The tp×dp engine
    bit-identity matrix LANDED with ISSUE 20 (tests/test_serve_tp.py's
    tpdp cells, slow-marked); the pool-sharding opt-in it uses is
    TestDpPool below."""

    def test_stacked_dense_rows_shard_slots_over_dp(self):
        # [slots, 1, S, KV, Dh]: dp on the slot axis, tp on KV.
        assert leaf_spec("cached_key", (4, 1, 64, 4, 16), 2,
                         dp_size=2) == P("dp", None, None, "tp", None)
        assert leaf_spec("cached_value", (4, 1, 64, 4, 16), 1,
                         dp_size=2) == P("dp", None, None, None, None)

    def test_solo_dense_rows_never_shard_dp(self):
        # The solo cache [1, S, KV, Dh] has no slot axis.
        assert leaf_spec("cached_key", (1, 64, 4, 16), 2,
                         dp_size=2) == P(None, None, "tp", None)

    def test_per_slot_bookkeeping_shards_over_dp(self):
        assert leaf_spec("block_table", (4, 8), 2, dp_size=2) == \
            P("dp", None)
        assert leaf_spec("cache_index", (4,), 2, dp_size=2) == P("dp")
        assert leaf_spec("pos_index", (4,), 1, dp_size=4) == P("dp")

    def test_paged_pool_replicates_over_dp(self):
        # The pool is SHARED across slots: any slot's table may point
        # at any block — dp cannot shard it, tp still shards heads.
        assert leaf_spec("pool_key", (25, 8, 4, 16), 2, dp_size=2) == \
            P(None, None, "tp", None)
        assert leaf_spec("pool_key", (25, 8, 4, 16), 1, dp_size=2) == \
            P()

    def test_untileable_slots_fall_back(self):
        # 3 slots over dp=2: the dp component drops, tp survives.
        assert leaf_spec("cached_key", (3, 1, 64, 4, 16), 2,
                         dp_size=2) == P(None, None, None, "tp", None)

    def test_logits_shard_slots_and_vocab(self):
        assert logits_spec((8, 64), 2, dp_size=2) == P("dp", "tp")
        assert logits_spec((8, 64), 1, dp_size=2) == P("dp", None)
        assert logits_spec((7, 64), 2, dp_size=2) == P(None, "tp")

    def test_cache_specs_thread_dp_through(self):
        tree = {
            "attn": {
                "pool_key": arr(25, 8, 4, 16),
                "block_table": arr(4, 8),
                "cache_index": arr(4),
            },
        }
        specs = cache_specs(tree, 2, dp_size=2)
        assert specs["attn"]["pool_key"] == P(None, None, "tp", None)
        assert specs["attn"]["block_table"] == P("dp", None)
        assert specs["attn"]["cache_index"] == P("dp")

    def test_defaults_keep_tp_only_layout(self):
        # dp_size default 1: bit-for-bit the PR 10 behavior.
        assert leaf_spec("cached_key", (4, 1, 64, 4, 16), 2) == \
            P(None, None, None, "tp", None)
        assert leaf_spec("block_table", (4, 8), 2) == P()


class TestDpPool:
    """Pod-scale decode (ISSUE 20): with ``dp_pool=True`` the paged
    pool's BLOCK axis shards over dp — legal only because the engine
    allocates each dp shard's slots exclusively from that shard's
    ``shard_block_extent`` slice, so no slot's table ever references a
    block outside its own shard's tile. Pure spec/extent math here; the
    device-level pins (per-device pool shape, extent containment
    across an occupancy walk, ingest landing on the seating shard) are
    the tpdp cells in tools/serve_tp_check.py."""

    def test_dp_pool_shards_block_axis(self):
        # [nb, blk, KV, Dh]: dp on blocks, tp on KV — the 2-D layout.
        assert leaf_spec("pool_key", (34, 8, 4, 16), 2, dp_size=2,
                         dp_pool=True) == P("dp", None, "tp", None)
        assert leaf_spec("pool_value_scale", (34, 8, 4), 2, dp_size=2,
                         dp_pool=True) == P("dp", None, "tp")

    def test_dp_pool_untileable_blocks_fall_back(self):
        # 33 blocks over dp=2: the dp component drops (the engine
        # prevents this case by rounding kv_blocks up to a dp multiple
        # — extents must coincide with XLA tile boundaries).
        assert leaf_spec("pool_key", (33, 8, 4, 16), 2, dp_size=2,
                         dp_pool=True) == P(None, None, "tp", None)

    def test_dp_pool_off_keeps_replicated_pool(self):
        assert leaf_spec("pool_key", (34, 8, 4, 16), 2, dp_size=2,
                         dp_pool=False) == P(None, None, "tp", None)

    def test_cache_specs_thread_dp_pool(self):
        tree = {"attn": {"pool_key": arr(34, 8, 4, 16),
                         "block_table": arr(4, 8)}}
        specs = cache_specs(tree, 2, dp_size=2, dp_pool=True)
        assert specs["attn"]["pool_key"] == P("dp", None, "tp", None)
        assert specs["attn"]["block_table"] == P("dp", None)

    def test_slot_spec_tiles_or_replicates(self):
        assert slot_spec((4, 64), 2) == P("dp", None)
        assert slot_spec((4,), 2) == P("dp")
        assert slot_spec((3, 64), 2) == P()   # untileable
        assert slot_spec((4, 64), 1) == P()   # dp=1: the old layout

    def test_shard_of_slot_slices_the_slot_axis(self):
        # 4 slots over dp=2: slots 0-1 -> shard 0, slots 2-3 -> shard 1.
        assert [shard_of_slot(s, 4, 2) for s in range(4)] == \
            [0, 0, 1, 1]
        assert shard_of_slot(3, 4, 1) == 0

    def test_shard_block_extent_partitions_the_pool(self):
        # 34 blocks over dp=2, block 0 reserved (garbage): shard 0 owns
        # [1, 17), shard 1 owns [17, 34) — disjoint, covering, and each
        # lo/hi a multiple of the 17-block XLA tile (except the
        # reserved clamp).
        assert shard_block_extent(0, 34, 2) == (1, 17)
        assert shard_block_extent(1, 34, 2) == (17, 34)
        # dp=1 (and the None-shard path): the whole pool minus reserve.
        assert shard_block_extent(0, 34, 1) == (1, 34)

    def test_extents_cover_disjointly(self):
        for dp in (2, 3, 4):
            nb = 12 * dp
            spans = [shard_block_extent(i, nb, dp) for i in range(dp)]
            assert spans[0][0] == 1          # reserve clamped out
            assert spans[-1][1] == nb
            for (_, hi), (lo2, _) in zip(spans, spans[1:]):
                assert hi == lo2             # no gap, no overlap

    def test_dp_size_of_reads_the_axis(self):
        class FakeDevices:
            size = 4

        class FakeMesh:
            devices = FakeDevices()
            shape = {"tp": 2, "dp": 2}

        assert dp_size_of(FakeMesh()) == 2
        assert dp_size_of(None) == 1


class TestShipSpecs:
    """Shard layout of shipped-KV wire rows (serve/disagg.py): each
    [R, KV, Dh] wire leaf head-shards like the pool leaf its rows land
    in, so the disaggregated path composes with tp>1."""

    def test_wire_rows_head_shard_like_the_pool(self):
        rows = {"block_0/attn": {"key": arr(16, 4, 8),
                                 "value": arr(16, 4, 8)}}
        specs = ship_specs(rows, 2)
        assert specs["block_0/attn"]["key"] == P(None, "tp", None)
        assert specs["block_0/attn"]["value"] == P(None, "tp", None)

    def test_untileable_heads_replicate(self):
        rows = {"a": {"key": arr(16, 3, 8), "value": arr(16, 3, 8)}}
        specs = ship_specs(rows, 2)
        assert specs["a"]["key"] == P()

    def test_accepts_bare_shapes(self):
        specs = ship_specs({"a": {"key": (16, 4, 8)}}, 4)
        assert specs["a"]["key"] == P(None, "tp", None)

    def test_tp1_replicates(self):
        specs = ship_specs(
            {"a": {"key": arr(16, 4, 8), "value": arr(16, 4, 8)}}, 1
        )
        assert specs["a"]["key"] == P()


class TestMeshDebug:
    def test_no_mesh_is_single_device(self):
        assert mesh_debug(None) == {"devices": 1}
        assert tp_size_of(None) == 1

    def test_mesh_shape_surfaces(self):
        class FakeDevices:
            size = 4

        class FakeMesh:
            devices = FakeDevices()
            shape = {"dp": 2, "tp": 2}

        info = mesh_debug(FakeMesh())
        assert info == {"devices": 4, "axes": {"dp": 2, "tp": 2}}
        assert tp_size_of(FakeMesh()) == 2
