"""End-to-end tests against real OS processes (tier-4 analog, SURVEY.md §4):
the controller reconciles a submitted TPUJob into pods, the local executor
launches each pod's command as a subprocess running the fake-workload HTTP
server, and the harness drives lifecycle through real HTTP — /tfconfig echo,
/exit fault injection — asserting status transitions and GC."""

import json
import sys
import threading
import time
import urllib.request

import pytest

from tf_operator_tpu.api import constants
from tf_operator_tpu.controller.jobcontroller import JobControllerConfig
from tf_operator_tpu.controller.tpujob_controller import TPUJobController
from tf_operator_tpu.runtime import objects
from tf_operator_tpu.runtime.client import NotFound
from tf_operator_tpu.runtime.executor import LocalProcessExecutor
from tf_operator_tpu.runtime.gc import OwnerGarbageCollector
from tf_operator_tpu.runtime.memcluster import InMemoryCluster

SERVER_CMD = [sys.executable, "-m", "tf_operator_tpu.harness.test_server"]


@pytest.fixture()
def stack():
    client = InMemoryCluster()
    tc = TPUJobController(
        client,
        JobControllerConfig(reconcile_period=0.2, informer_resync=0.5, threadiness=2),
    )
    executor = LocalProcessExecutor(client)
    collector = OwnerGarbageCollector(client)
    stop = threading.Event()
    threading.Thread(target=tc.run, args=(stop,), daemon=True).start()
    executor.start(stop)
    collector.start(stop)
    time.sleep(0.3)
    yield client, executor
    stop.set()
    time.sleep(0.3)


def submit_job(client, name="e2e", workers=2, restart_policy=None, ttl=None,
               clean_policy=None):
    spec = {
        "replicaSpecs": {
            "Worker": {
                "replicas": workers,
                "template": {
                    "spec": {
                        "containers": [
                            {
                                "name": constants.DEFAULT_CONTAINER_NAME,
                                "image": "local",
                                "command": SERVER_CMD,
                            }
                        ]
                    }
                },
            }
        }
    }
    if restart_policy:
        spec["replicaSpecs"]["Worker"]["restartPolicy"] = restart_policy
    if ttl is not None:
        spec["ttlSecondsAfterFinished"] = ttl
    if clean_policy:
        spec["cleanPodPolicy"] = clean_policy
    return client.create(
        objects.TPUJOBS,
        {
            "apiVersion": constants.API_VERSION,
            "kind": constants.KIND,
            "metadata": {"name": name, "namespace": "default"},
            "spec": spec,
        },
    )


def test_unexecutable_command_fails_pod_with_127(stack):
    """A command that cannot exec must surface as exitCode 127 (the
    kubelet convention) through the pdeathsig exec shim — the same
    terminal signal the old parent-side spawn-failure path produced —
    and with restartPolicy Never the pod goes Failed, no restart loop."""
    client, executor = stack
    pod = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"namespace": "default", "name": "bad-cmd"},
        "spec": {
            "restartPolicy": "Never",
            "containers": [{
                "name": constants.DEFAULT_CONTAINER_NAME,
                "command": ["/definitely/not/a/real/binary"],
            }],
        },
    }
    client.create(objects.PODS, pod)

    def failed_with_127():
        got = client.get(objects.PODS, "default", "bad-cmd")
        if objects.pod_phase(got) != objects.FAILED:
            return False
        statuses = got.get("status", {}).get("containerStatuses", [])
        return any(
            s.get("state", {}).get("terminated", {}).get("exitCode") == 127
            for s in statuses
        )

    wait_for(failed_with_127, desc="bad-cmd pod Failed exitCode 127")


def wait_for(predicate, timeout=15.0, interval=0.1, desc="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        result = predicate()
        if result:
            return result
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {desc}")


def job_condition(client, name, ctype):
    def check():
        try:
            job = client.get(objects.TPUJOBS, "default", name)
        except NotFound:
            return False
        return any(
            c["type"] == ctype and c["status"] == "True"
            for c in job.get("status", {}).get("conditions", [])
        )

    return check


def http_get(executor, pod_name, path, timeout=3.0):
    addr = wait_for(lambda: executor.resolve(pod_name), desc=f"port for {pod_name}")
    url = f"http://{addr[0]}:{addr[1]}{path}"

    def try_get():
        try:
            with urllib.request.urlopen(url, timeout=timeout) as resp:
                return json.loads(resp.read())
        except OSError:
            return None

    return wait_for(try_get, desc=f"GET {url}")


class TestHappyPath:
    def test_submit_run_terminate_succeed_gc(self, stack):
        client, executor = stack
        submit_job(client, "e2e", workers=2, ttl=1, clean_policy="All")

        wait_for(job_condition(client, "e2e", "Running"), desc="Running")

        # Reach replica 0 through the service-proxy analog: TF_CONFIG echo.
        cfg = http_get(executor, "e2e-worker-0", "/tfconfig")
        assert cfg["task"] == {"type": "worker", "index": 0}
        assert len(cfg["cluster"]["worker"]) == 2
        # The cluster spec was rewritten to reachable localhost addrs.
        host0 = cfg["cluster"]["worker"][0]
        assert host0.startswith("127.0.0.1:")

        # Fault-inject clean exits on both replicas (the reference's
        # terminateReplica flow, test_runner.py:285-318).
        http_get(executor, "e2e-worker-0", "/exit?exitCode=0")
        http_get(executor, "e2e-worker-1", "/exit?exitCode=0")

        # With ttl=1 the job self-deletes ~1s after succeeding, so "Succeeded
        # observed" and "job gone" are both valid outcomes of the poll race.
        succeeded = job_condition(client, "e2e", "Succeeded")

        def job_gone():
            try:
                client.get(objects.TPUJOBS, "default", "e2e")
                return False
            except NotFound:
                return True

        wait_for(lambda: succeeded() or job_gone(), desc="Succeeded-or-reaped")
        wait_for(job_gone, timeout=60, desc="TTL deletion")
        wait_for(
            lambda: not client.list(objects.PODS)
            and not client.list(objects.SERVICES),
            desc="owned resources GC",
        )

    def test_ps_worker_cluster_spec_all_replicas(self, stack):
        """BASELINE configs[2] rung: a 2 PS + 4 Worker job where EVERY
        replica's injected TF_CONFIG carries the full cluster map and its
        own (type, index) identity — the between-graph PS/Worker contract
        (reference controller_tensorflow.go:66-96)."""
        client, executor = stack
        container = {
            "name": constants.DEFAULT_CONTAINER_NAME,
            "image": "local",
            "command": SERVER_CMD,
        }
        # Both replica sets must be in the spec BEFORE create: the
        # controller reconciles on the ADDED event, and pods keep their
        # baked-in TF_CONFIG (no rebuild on spec change).
        client.create(
            objects.TPUJOBS,
            {
                "apiVersion": constants.API_VERSION,
                "kind": constants.KIND,
                "metadata": {"name": "psjob", "namespace": "default"},
                "spec": {
                    "replicaSpecs": {
                        "Worker": {
                            "replicas": 4,
                            "template": {"spec": {"containers": [dict(container)]}},
                        },
                        "PS": {
                            "replicas": 2,
                            "template": {"spec": {"containers": [dict(container)]}},
                        },
                    }
                },
            },
        )
        # Running requires every replica type fully active — all 6 pods.
        wait_for(job_condition(client, "psjob", "Running"), desc="psjob Running")
        for rtype, count in (("worker", 4), ("ps", 2)):
            for i in range(count):
                cfg = http_get(executor, f"psjob-{rtype}-{i}", "/tfconfig")
                assert cfg["task"] == {"type": rtype, "index": i}
                assert len(cfg["cluster"]["worker"]) == 4
                assert len(cfg["cluster"]["ps"]) == 2
                assert cfg["environment"] == "cloud"
        # workers terminate cleanly; PS roles are long-running by design and
        # the job must succeed on worker completion (no chief present).
        for i in range(4):
            http_get(executor, f"psjob-worker-{i}", "/exit?exitCode=0")
        wait_for(job_condition(client, "psjob", "Succeeded"),
                 desc="psjob Succeeded")

    def test_worker0_identity_and_topology_echo(self, stack):
        client, executor = stack
        submit_job(client, "ident", workers=2)
        wait_for(job_condition(client, "ident", "Running"), desc="Running")
        top = http_get(executor, "ident-worker-1", "/tfconfig")
        assert top["task"]["index"] == 1


class TestFaultInjection:
    def test_retryable_exit_restarts_and_recovers(self, stack):
        client, executor = stack
        submit_job(client, "flaky", workers=2, restart_policy="ExitCode")
        wait_for(job_condition(client, "flaky", "Running"), desc="Running")

        # SIGKILL-style death on worker 0: retryable → controller deletes the
        # pod, recreates it, executor relaunches. The Restarting condition is
        # transient (replaced by Running within one reconcile period), so the
        # durable signals are restartCount and recovery to Running.
        http_get(executor, "flaky-worker-0", "/exit?exitCode=137")

        def restart_counted():
            job = client.get(objects.TPUJOBS, "default", "flaky")
            return job.get("status", {}).get("restartCount", 0) >= 1

        wait_for(restart_counted, desc="restartCount")
        wait_for(job_condition(client, "flaky", "Running"), timeout=60, desc="Running again")

        # Now finish cleanly.
        http_get(executor, "flaky-worker-0", "/exit?exitCode=0")
        http_get(executor, "flaky-worker-1", "/exit?exitCode=0")
        wait_for(job_condition(client, "flaky", "Succeeded"), timeout=60, desc="Succeeded")

    def test_permanent_exit_fails_job(self, stack):
        client, executor = stack
        submit_job(client, "doomed", workers=1, restart_policy="ExitCode")
        wait_for(job_condition(client, "doomed", "Running"), desc="Running")
        http_get(executor, "doomed-worker-0", "/exit?exitCode=1")
        wait_for(job_condition(client, "doomed", "Failed"), desc="Failed")


class TestNoLeakedProcesses:
    @pytest.mark.skipif(
        sys.platform != "linux",
        reason="PDEATHSIG is Linux-only (the feature degrades to a no-op "
        "elsewhere by design); also relies on procps ps output",
    )
    def test_sigkilled_operator_leaves_no_children(self, tmp_path):
        """A SIGKILLed operator (pytest-timeout reaper, OOM kill) must not
        leak its pod processes: PDEATHSIG tears the tree down (observed in
        the wild as leaked operators churning 90% of a CI core)."""
        import os
        import signal as signal_mod
        import socket
        import subprocess

        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "tf_operator_tpu.cli.operator",
                "--serve", str(port), "--local-executor",
                "--reconcile-period", "0.3", "--exit-with-parent",
            ],
            env=env,
            stdout=open(tmp_path / "op.log", "wb"), stderr=subprocess.STDOUT,
        )
        base = f"http://127.0.0.1:{port}"
        deadline = time.monotonic() + 90
        up = False
        while time.monotonic() < deadline and not up:
            try:
                urllib.request.urlopen(base + "/api/tpujobs", timeout=1)
                up = True
            except Exception:
                assert proc.poll() is None, open(tmp_path / "op.log").read()
                time.sleep(0.2)
        assert up, "operator never came up"

        try:
            # A job whose pod is a real long-running process.
            from tf_operator_tpu.client import TPUJobClient
            from tf_operator_tpu.runtime.restclient import RestClusterClient

            cli = TPUJobClient(RestClusterClient(base))
            cli.create({
                "apiVersion": constants.API_VERSION,
                "kind": constants.KIND,
                "metadata": {"name": "leakcheck", "namespace": "default"},
                "spec": {"replicaSpecs": {"Worker": {"replicas": 1, "template": {
                    "spec": {"containers": [{
                        "name": constants.DEFAULT_CONTAINER_NAME,
                        "image": "local", "command": SERVER_CMD,
                    }]}}}}},
            })
            pod_pid = None
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline and pod_pid is None:
                out = subprocess.run(
                    ["ps", "-eo", "pid,ppid,args"],
                    capture_output=True, text=True,
                ).stdout
                for line in out.splitlines():
                    cols = line.split(None, 2)
                    if (len(cols) == 3 and cols[1] == str(proc.pid)
                            and "test_server" in cols[2]):
                        pod_pid = int(cols[0])
                time.sleep(0.3)
            assert pod_pid is not None, "pod process never appeared"

            # SIGKILL the operator: no cleanup code can run; kernel-side
            # PDEATHSIG must still reap the pod process.
            proc.send_signal(signal_mod.SIGKILL)
            proc.wait(timeout=10)
            deadline = time.monotonic() + 15
            gone = False
            while time.monotonic() < deadline and not gone:
                try:
                    os.kill(pod_pid, 0)
                    time.sleep(0.2)
                except ProcessLookupError:
                    gone = True
            assert gone, f"pod process {pod_pid} leaked after operator SIGKILL"
        finally:
            if proc.poll() is None:
                proc.kill()

    @pytest.mark.skipif(
        sys.platform != "linux", reason="ppid semantics exercised on Linux CI"
    )
    def test_orphaned_operator_exits(self, tmp_path):
        """--exit-with-parent must fire on parent PROCESS death — and must
        NOT fire when merely the spawning THREAD exits (the PDEATHSIG
        pitfall that killed the CI workflow's operator: the deploy step's
        worker thread finished and took the operator with it)."""
        import os
        import subprocess
        import textwrap

        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        # An intermediate parent that spawns the operator FROM A THREAD,
        # waits past the thread's exit (operator must survive), prints the
        # operator pid, then exits (operator must die).
        script = textwrap.dedent(
            """
            import subprocess, sys, threading, time
            holder = {}
            def spawn():
                holder["p"] = subprocess.Popen([
                    sys.executable, "-m", "tf_operator_tpu.cli.operator",
                    "--exit-with-parent",
                ])
            t = threading.Thread(target=spawn)
            t.start(); t.join()          # the spawning thread is now gone
            time.sleep(3.0)              # operator must still be alive
            rc = holder["p"].poll()
            print(f"pid={holder['p'].pid} rc={rc}", flush=True)
            """
        )
        out = subprocess.run(
            [sys.executable, "-c", script], env=env,
            capture_output=True, text=True, timeout=120,
        )
        assert out.returncode == 0, out.stderr
        fields = dict(kv.split("=") for kv in out.stdout.split())
        assert fields["rc"] == "None", (
            f"operator died while its parent was alive (rc={fields['rc']}) "
            "— the spawning-thread-exit pitfall is back"
        )
        pid = int(fields["pid"])
        # The intermediate parent has now exited; the orphaned operator
        # must notice (ppid -> 1) and exit within the poll interval.
        deadline = time.monotonic() + 15
        gone = False
        while time.monotonic() < deadline and not gone:
            try:
                os.kill(pid, 0)
                time.sleep(0.3)
            except ProcessLookupError:
                gone = True
        assert gone, f"orphaned operator {pid} still running"
