"""KV memory hierarchy (serve/tier.py): the host-RAM block tier —
spill on eviction, restore on resume, tier-aware admission.

The pins mirror test_serve_prefix_pull.py's discipline — every restored
decode is bit-identical to the solo ``generate`` oracle (greedy AND
sampled, dense AND kv8) with zero decode recompiles — plus the tier's
own contracts:

- spill: when pool pressure reclaims a retained prefix hold, its exact
  entry lands in the host tier as the PR-14 wire payload instead of
  vanishing (blocks back in the pool, digest advertised as warm);
- restore: a later identical prompt exact-joins the restored blocks —
  prefill skipped for the whole prompt, decode bit-identical to a
  never-spilled run;
- tier-off (`--host-tier-bytes 0`): the PR 16 accounting exactly — no
  ``tier`` section in kv_debug, nothing advertised, evictions simply
  free;
- can-restore wait: a tier hit the pool cannot hold yet requeues
  (outcome "exhausted"), distinct from a plain must-wait miss;
- export: ``GET /prefix/<digest>`` answers from the holder's host tier
  too (the stored payload IS the wire format — no device work);
- session prefetch: a ``session``-keyed enqueue pre-warms its prefix;
- typed ``tier_miss``: an advertised-warm digest whose payload is gone
  answers 404 ``tier_miss`` (retryable=False — the router degrades to
  local prefill, it does not retry the same replica).

HostTier itself (byte budget, LRU eviction, refusal) is unit-tested
jax-free at the bottom. The fleet chaos case (kill the warm holder
mid-restore on both cluster backends, zero lost) lives with the other
router chaos in test_fleet_chaos.py; the bench-scale acceptance pair is
pinned here structurally (slow).
"""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tf_operator_tpu.models.transformer import (
    Transformer,
    TransformerConfig,
    generate,
)
from tf_operator_tpu.serve.disagg import chain_digests, decode_shipment
from tf_operator_tpu.serve.engine import ContinuousEngine
from tf_operator_tpu.serve.httpapi import readiness_payload
from tf_operator_tpu.serve.resilience import PrefixNotFound, TierMiss
from tf_operator_tpu.serve.scheduler import ContinuousScheduler, ServeRequest
from tf_operator_tpu.serve.tier import HostTier, payload_nbytes

pytestmark = [pytest.mark.serve, pytest.mark.tier]

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_layers=2, n_heads=2, d_ff=64,
    max_seq_len=64, dtype=jnp.float32,
)
BLOCK = 8


@pytest.fixture(scope="module")
def params():
    return Transformer(CFG).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]


def prompt_of(p: int, seed: int) -> np.ndarray:
    return np.random.default_rng(seed).integers(
        0, CFG.vocab_size, (1, p)
    ).astype(np.int32)


def solo(cfg, params, prompt, steps, *, temperature=0.0, seed=0):
    kw = {}
    if temperature > 0:
        kw = dict(temperature=temperature, rng=jax.random.PRNGKey(seed))
    return np.asarray(
        generate(cfg, params, jnp.asarray(prompt), steps, **kw)
    )[0].tolist()


def mk_sched(params, *, cfg=CFG, retain=32, max_slots=2, kv_blocks=None,
             tier_bytes=64 << 20):
    """A paged engine with retention ON and (tier_bytes > 0) the host
    tier attached — the serve_lm --host-tier-bytes wiring — wrapped in
    a started scheduler."""
    kw = {} if kv_blocks is None else {"kv_blocks": kv_blocks}
    eng = ContinuousEngine(
        cfg, params, max_slots=max_slots, kv_paged=True, kv_block=BLOCK,
        **kw,
    )
    eng.prefix_retain_max = retain
    eng.prefix_advertise_max = 32
    if tier_bytes:
        eng.host_tier = HostTier(tier_bytes)
    return ContinuousScheduler(eng).start()


def exact_digest(prompt) -> str:
    return chain_digests(np.asarray(prompt[0], np.int32), BLOCK)[-1]


def force_spill(sched):
    """Reclaim EVERY retained prefix hold under simulated pool
    pressure (the PR 16 oldest-first path) — with a tier attached the
    dying exact entries spill; without one they just free."""
    sched.call_engine(lambda e: e._evict_retained(until_free=10 ** 9))


# ---------------------------------------------------------------------------
# spill → restore, bit-identical (dense)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("temperature,seed", [(0.0, 0), (0.9, 11)],
                         ids=["greedy", "sampled"])
def test_spill_restore_bit_identical(params, temperature, seed):
    """The tentpole pin: serve once (entry retained), evict under
    pressure (entry SPILLS to host), serve the identical prompt again
    — admission restores the spilled blocks, the plan exact-joins them
    (prefill skipped for the whole prompt), and the decode is
    bit-identical to the never-spilled solo oracle with zero decode
    recompiles."""
    prompt = prompt_of(13, 70 if temperature == 0 else 71)
    steps = 8
    oracle = solo(CFG, params, prompt, steps,
                  temperature=temperature, seed=seed)
    sched = mk_sched(params)
    eng = sched.engine
    try:
        r1 = sched.submit_request(ServeRequest(
            prompt, steps, temperature=temperature, seed=seed,
        ), timeout=60.0)
        assert r1.out == oracle
        force_spill(sched)
        # The entry left HBM for the host tier: blocks back in the
        # pool, digest now advertised as WARM (not hot).
        assert eng.blocks.used == 0
        assert exact_digest(prompt) not in sched.advertised_prefixes()
        assert exact_digest(prompt) in sched.advertised_tier_prefixes()
        saved0 = sched.debug_snapshot()["kv_cache"]["prefill_tokens_saved"]
        r2 = sched.submit_request(ServeRequest(
            prompt, steps, temperature=temperature, seed=seed,
        ), timeout=60.0)
        snap = sched.debug_snapshot()
        assert r2.out == oracle, (r2.out, oracle)
        assert r2.tier_join, "admission did not restore from the tier"
        assert eng.tier_restores >= 1
        saved = snap["kv_cache"]["prefill_tokens_saved"] - saved0
        assert saved == prompt.shape[1], "restore did not skip prefill"
        assert snap["decode_step_compiles"] == snap["warmup_compiles"]
        tier = snap["kv_cache"]["tier"]
        assert tier["spills"] >= 1 and tier["hits"] >= 1
        assert tier["restore_tokens"] >= prompt.shape[1]
    finally:
        sched.stop(timeout=30.0)


def test_session_resume_restores_turn_prefix(params):
    """The many-session resume shape the bench runs at scale: turn 2's
    prompt EXTENDS turn 1's (block-aligned), the tier restores the
    spilled turn-1 prefix, and only the extension prefills."""
    turn1 = prompt_of(16, 72)  # block-aligned: its digest is in every
    steps = 6                  # extension's chain
    ext = np.concatenate(
        [turn1, np.asarray(solo(CFG, params, turn1, steps),
                           np.int32)[None, :8],
         prompt_of(8, 73)], axis=1,
    )
    sched = mk_sched(params)
    eng = sched.engine
    try:
        sched.submit_request(ServeRequest(turn1, steps, session="s0"),
                             timeout=60.0)
        force_spill(sched)
        assert eng.blocks.used == 0
        oracle = solo(CFG, params, ext, steps)
        r2 = sched.submit_request(ServeRequest(ext, steps, session="s0"),
                                  timeout=60.0)
        assert r2.out == oracle, (r2.out, oracle)
        assert eng.tier_restores >= 1
        # Only the 16 aligned turn-1 tokens restored; the rest
        # prefilled locally — partial restore, not all-or-nothing.
        assert eng.tier_restore_tokens >= 16
    finally:
        sched.stop(timeout=30.0)


def test_session_prefetch_prewarms(params):
    """A ``session``-keyed enqueue posts a fire-and-forget restore that
    runs loop-serialized before admission — either way (prefetch or
    admission-time restore wins the race) the prompt exact-joins and
    never re-prefills."""
    prompt = prompt_of(13, 74)
    steps = 6
    oracle = solo(CFG, params, prompt, steps)
    sched = mk_sched(params)
    eng = sched.engine
    try:
        sched.submit_request(ServeRequest(prompt, steps, session="s1"),
                             timeout=60.0)
        force_spill(sched)
        saved0 = sched.debug_snapshot()["kv_cache"]["prefill_tokens_saved"]
        r2 = sched.submit_request(ServeRequest(prompt, steps,
                                               session="s1"), timeout=60.0)
        snap = sched.debug_snapshot()
        assert r2.out == oracle
        assert eng.tier_restores >= 1
        saved = snap["kv_cache"]["prefill_tokens_saved"] - saved0
        assert saved == prompt.shape[1]
        assert snap["decode_step_compiles"] == snap["warmup_compiles"]
    finally:
        sched.stop(timeout=30.0)


# ---------------------------------------------------------------------------
# kv8: int8 pools spill WITH their scale sidecars
# ---------------------------------------------------------------------------


class TestKv8Tier:
    @pytest.fixture(scope="class")
    def cfg8(self):
        from dataclasses import replace
        return replace(CFG, kv_int8=True)

    @pytest.fixture(scope="class")
    def p8(self, cfg8):
        return Transformer(cfg8).init(
            jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
        )["params"]

    @pytest.mark.parametrize("temperature,seed", [(0.0, 0), (0.9, 5)],
                             ids=["greedy", "sampled"])
    def test_kv8_spill_restore_bit_identical(self, cfg8, p8,
                                             temperature, seed):
        prompt = prompt_of(13, 75 if temperature == 0 else 76)
        steps = 8
        oracle = solo(cfg8, p8, prompt, steps,
                      temperature=temperature, seed=seed)
        sched = mk_sched(p8, cfg=cfg8)
        try:
            r1 = sched.submit_request(ServeRequest(
                prompt, steps, temperature=temperature, seed=seed,
            ), timeout=60.0)
            assert r1.out == oracle
            force_spill(sched)
            # The spilled payload carries the f32 scale-row sidecars —
            # read it back through the export fallback (the tier stores
            # the wire format verbatim).
            wire = json.loads(json.dumps(
                sched.export_prefix(exact_digest(prompt))
            ))
            parts = set().union(*(set(kv)
                                  for kv in wire["rows"].values()))
            assert {"key_scale", "value_scale"} <= parts
            r2 = sched.submit_request(ServeRequest(
                prompt, steps, temperature=temperature, seed=seed,
            ), timeout=60.0)
            snap = sched.debug_snapshot()
            assert r2.out == oracle, (r2.out, oracle)
            assert r2.tier_join
            assert snap["decode_step_compiles"] == snap["warmup_compiles"]
        finally:
            sched.stop(timeout=30.0)


# ---------------------------------------------------------------------------
# tier-off: the PR 16 accounting, exactly
# ---------------------------------------------------------------------------


def test_tier_off_accounting_unchanged(params):
    """--host-tier-bytes 0: no tier section in kv_debug, nothing
    advertised warm, evictions free without spilling, restore reports
    miss — byte-for-byte the PR 16 snapshot shape."""
    prompt = prompt_of(13, 77)
    sched = mk_sched(params, tier_bytes=0)
    eng = sched.engine
    try:
        sched.submit_request(ServeRequest(prompt, 6), timeout=60.0)
        force_spill(sched)
        assert eng.blocks.used == 0
        kv = sched.debug_snapshot()["kv_cache"]
        assert "tier" not in kv
        assert sched.advertised_tier_prefixes() == []
        assert eng.tier_probe(np.asarray(prompt)) is False
        hold, outcome = sched.call_engine(
            lambda e: e.restore_from_tier(np.asarray(prompt))
        )
        assert hold is None and outcome == "miss"
        with pytest.raises(PrefixNotFound):
            sched.export_prefix(exact_digest(prompt))
    finally:
        sched.stop(timeout=30.0)


# ---------------------------------------------------------------------------
# tier-aware admission: must-wait vs can-restore
# ---------------------------------------------------------------------------


def test_exhausted_pool_is_can_restore_not_recompute(params):
    """A tier hit the pool cannot hold yet reports outcome "exhausted"
    (the can-restore wait) while ``tier_probe`` stays True — and once
    capacity frees, the SAME prompt restores and serves bit-identically
    without ever recomputing its prefix."""
    prompt = prompt_of(13, 78)
    steps = 6
    oracle = solo(CFG, params, prompt, steps)
    sched = mk_sched(params, kv_blocks=8, max_slots=1)
    eng = sched.engine
    try:
        r1 = sched.submit_request(ServeRequest(prompt, steps),
                                  timeout=60.0)
        assert r1.out == oracle
        force_spill(sched)
        # Artificially exhaust the pool (live work holds every block).
        grabbed = sched.call_engine(
            lambda e: e.blocks.alloc(e.blocks.free_blocks)
        )
        assert grabbed, "pool should have had free blocks to grab"
        assert eng.tier_probe(np.asarray(prompt)) is True
        hold, outcome = sched.call_engine(
            lambda e: e.restore_from_tier(np.asarray(prompt),
                                          reserve_steps=steps)
        )
        assert hold is None and outcome == "exhausted"
        # The entry survived the failed attempt — capacity frees, the
        # restore lands.
        sched.call_engine(lambda e: e._free_blocks(grabbed))
        r2 = sched.submit_request(ServeRequest(prompt, steps),
                                  timeout=60.0)
        assert r2.out == oracle
        assert r2.tier_join
    finally:
        sched.stop(timeout=30.0)


# ---------------------------------------------------------------------------
# fleet surfaces: export fallback, /healthz advertisement, typed miss
# ---------------------------------------------------------------------------


def test_export_answers_from_tier(params):
    """GET /prefix/<digest> on a spilled entry: the holder answers with
    the STORED wire payload (no device work, prefix_exports counted) —
    a peer's pull decodes it exactly like a hot export."""
    prompt = prompt_of(13, 79)
    sched = mk_sched(params)
    try:
        sched.submit_request(ServeRequest(prompt, 6), timeout=60.0)
        force_spill(sched)
        exports0 = sched.debug_snapshot()["kv_cache"]["prefix_exports"]
        wire = json.loads(json.dumps(
            sched.export_prefix(exact_digest(prompt))
        ))
        assert sched.debug_snapshot()["kv_cache"]["prefix_exports"] == (
            exports0 + 1
        )
        shp = decode_shipment(wire, expect_tokens=prompt[0])
        assert shp.tokens.tolist() == prompt[0].tolist()
        # Unknown digests still answer the typed prefix_not_found.
        with pytest.raises(PrefixNotFound):
            sched.export_prefix("ab" * 20)
    finally:
        sched.stop(timeout=30.0)


class _ProbeShape:
    active_slots = 0
    queue_depth = 0
    requests_done = 0
    tokens_generated = 0

    def __init__(self, sched):
        self._sched = sched

    def advertised_prefixes(self):
        return self._sched.advertised_prefixes()

    def advertised_tier_prefixes(self):
        return self._sched.advertised_tier_prefixes()


def test_readiness_advertises_tier_and_omits_when_empty(params):
    """/healthz: ``tier_prefixes`` carries the warm digests, capped by
    prefix_advertise_max like the hot list — and the key is OMITTED
    when the tier has nothing (the membership clear-on-absent
    contract)."""
    prompt = prompt_of(11, 80)
    sched = mk_sched(params)
    duck = _ProbeShape(sched)
    try:
        sched.submit_request(ServeRequest(prompt, 4), timeout=60.0)
        payload = readiness_payload(duck)
        assert "tier_prefixes" not in payload  # nothing spilled yet
        force_spill(sched)
        payload = readiness_payload(duck)
        assert exact_digest(prompt) in payload["tier_prefixes"]
        assert exact_digest(prompt) not in payload.get("prefixes", [])
        sched.engine.prefix_advertise_max = 0
        assert "tier_prefixes" not in readiness_payload(duck)
    finally:
        sched.engine.prefix_advertise_max = 32
        sched.stop(timeout=30.0)


def test_tier_miss_is_typed():
    """An advertised-warm digest whose payload is gone (evicted between
    probe and pull) answers the typed ``tier_miss`` 404 — jax-free, on
    the fleet fake, same shape a real replica serves."""
    from tf_operator_tpu.fleet.replica import FakeReplicaBackend
    from tf_operator_tpu.serve.resilience import (
        WIRE_CODES,
        http_status_of,
    )

    backend = FakeReplicaBackend(max_slots=2)
    backend.tier_prefixes = ["ab" * 20]
    with pytest.raises(TierMiss) as exc:
        backend.export_prefix("ab" * 20)
    assert exc.value.code == "tier_miss"
    assert exc.value.retryable is False
    assert http_status_of(exc.value) == 404
    assert "tier_miss" in WIRE_CODES
    # A digest never advertised stays the PR 16 typed answer.
    with pytest.raises(PrefixNotFound):
        backend.export_prefix("cd" * 20)
    # A stored tier payload serves the pull.
    backend.tier_store["ab" * 20] = {"version": 1, "tokens": [1, 2],
                                     "kv_block": 2}
    assert backend.export_prefix("ab" * 20)["tokens"] == [1, 2]


# ---------------------------------------------------------------------------
# HostTier unit pins (jax-free)
# ---------------------------------------------------------------------------


def _payload(tag: str, nbytes: int = 96) -> dict:
    import base64
    data = base64.b64encode(b"\x00" * nbytes).decode()
    return {
        "version": 1, "tokens": [1, 2, 3], "kv_block": 2,
        "digests": [f"{tag}-d0", f"{tag}-d1"],
        "rows": {"layer0": {"key": {"b64": data}}},
    }


def test_host_tier_lru_byte_budget():
    one = payload_nbytes(_payload("a"))
    tier = HostTier(2 * one)
    assert tier.put(_payload("a")) and tier.put(_payload("b"))
    assert len(tier) == 2 and tier.bytes_used == 2 * one
    # Touch a: b becomes the cold end; c evicts b, not a.
    assert tier.get("a-d1") is not None
    assert tier.put(_payload("c"))
    assert "b-d1" not in tier and "a-d1" in tier and "c-d1" in tier
    snap = tier.snapshot()
    assert snap["evictions"] == 1 and snap["entries"] == 2
    assert snap["bytes_used"] <= snap["capacity_bytes"]
    # Oversize payloads are refused, never raise (spill is
    # best-effort: the blocks were dying anyway).
    assert not HostTier(8).put(_payload("x"))
    # deepest: shortest-first chain resolves to the longest stored.
    assert tier.deepest(["a-d0", "a-d1"]) == "a-d1"
    assert tier.deepest(["zz"]) is None
    # advertise is MRU-first and capped.
    assert tier.advertise(1) == ["c-d1"]
    assert tier.advertise(0) == []
    # discard is idempotent and returns the bytes.
    used = tier.bytes_used
    tier.discard("c-d1")
    tier.discard("c-d1")
    assert tier.bytes_used == used - one


# ---------------------------------------------------------------------------
# bench acceptance pair (structural, slow)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_serve_bench_tier_structural():
    """tools/serve_bench.py --engine tier (BENCH_SMOKE): the ISSUE-17
    session-resume pair — host tier vs recompute at the identical HBM
    block budget. Capacity-style pins only: every turn of every session
    resolves on both legs, the tier leg's outputs MATCH the recompute
    leg's token-for-token (bench-scale bit-identity), restores actually
    fired, the saved ratio beats 1, and the TTFT ratio fields hardware
    rounds key on exist."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_SMOKE="1",
               PALLAS_AXON_POOL_IPS="")
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "serve_bench.py"),
         "--engine", "tier"],
        capture_output=True, text=True, timeout=420, env=env, cwd=repo,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    lines = [json.loads(raw) for raw in proc.stdout.splitlines()
             if raw.startswith("{")]
    tier = next(l for l in lines
                if l["metric"] == "serve_tier_resume_"
                                  "tokens_per_sec_mixed")
    base = next(l for l in lines
                if l["metric"] == "serve_tier_recompute_"
                                  "tokens_per_sec_mixed")
    sys.path.insert(0, repo)
    from tools.serve_bench import SMOKE_TIER_MIX as MIX

    n_turns = MIX["sessions"] * MIX["turns"]
    for leg in (tier, base):
        assert leg["requests"] == n_turns
        assert leg["errors"] == 0
        assert leg["generated_tokens"] == n_turns * MIX["steps"]
        assert leg["kv_pool_blocks"] == base["kv_pool_blocks"]
        assert leg["decode_step_compiles"] == leg["warmup_compiles"]
        assert leg["resume_ttft_p50_ms"] > 0
    assert tier["tiered"] and not base["tiered"]
    # The acceptance direction: the tier turned evictions back into
    # prefix joins the recompute leg had to re-prefill.
    assert tier["tier"]["spills"] > 0
    assert tier["tier"]["restores"] > 0
    assert tier["prefill_tokens_saved"] > base["prefill_tokens_saved"]
    assert tier["prefill_tokens_saved_vs_baseline"] > 1.0
    # Bench-scale bit-identity: greedy, identical seeded schedule.
    assert tier["outputs_match_baseline"] is True
    # The ratio fields hardware rounds key on.
    assert tier["resume_ttft_p50_vs_baseline"] > 0
    assert tier["baseline_resume_ttft_p50_ms"] > 0
    assert tier["vs_baseline"] > 0
    assert tier["host_cpus"] >= 1
