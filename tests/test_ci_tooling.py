"""Release builder, prow artifacts, checks, deploy, and the workflow DAG.

Parity targets: py/release.py + build_and_push_image.py (content-tagged
artifacts), py/prow.py (started/finished contract), py/py_checks.py (lint
gate), py/deploy.py (operator up/down), and the Argo E2E DAG
(workflows.libsonnet topology semantics)."""

import json
import os
import tarfile
import time

import pytest

from tf_operator_tpu.harness import prow
from tf_operator_tpu.harness.checks import run_checks
from tf_operator_tpu.harness.workflow import Step, Workflow
from tf_operator_tpu.release.build import build_release

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# ---------------------------------------------------------------------------
# prow artifacts
# ---------------------------------------------------------------------------


def test_prow_started_finished(tmp_path):
    d = str(tmp_path)
    started = prow.create_started(d, repo="org/tpu-operator", pull="123",
                                  repo_root=REPO_ROOT, now=1000)
    assert started["timestamp"] == 1000
    assert started["repos"] == {"org/tpu-operator": "123"}
    assert len(started["repo-version"]) == 40  # a real git sha

    finished = prow.create_finished(d, False, {"e2e": "failed"}, now=2000)
    assert finished["result"] == "FAILURE" and not finished["passed"]

    on_disk = json.load(open(tmp_path / "finished.json"))
    assert on_disk["metadata"] == {"e2e": "failed"}
    assert json.load(open(tmp_path / "started.json"))["timestamp"] == 1000


# ---------------------------------------------------------------------------
# release build
# ---------------------------------------------------------------------------


def test_release_build_manifest_and_tarball(tmp_path):
    out = str(tmp_path / "dist")
    manifest = build_release(REPO_ROOT, out)
    assert manifest["git_sha"] != "unknown"
    assert manifest["name"].startswith("tpu-operator-0.")
    tar_path = os.path.join(out, manifest["artifact"])
    with tarfile.open(tar_path) as tar:
        names = tar.getnames()
    root = manifest["name"]
    assert f"{root}/tf_operator_tpu/version.py" in names
    assert f"{root}/bench.py" in names
    assert all(n.startswith(root + "/") for n in names)

    # content digest is deterministic across rebuilds
    manifest2 = build_release(REPO_ROOT, str(tmp_path / "dist2"))
    assert manifest2["content_digest"] == manifest["content_digest"]


def test_release_image_context_is_runnable(tmp_path):
    """--image-context stages Dockerfile + flattened sources such that the
    image ENTRYPOINT module resolves from the staged context (parity:
    build/images/tf_operator/Dockerfile builds operators+dashboard into one
    image)."""
    import subprocess
    import sys

    from tf_operator_tpu.release.build import build_image_context

    out = str(tmp_path / "dist")
    manifest = build_release(REPO_ROOT, out)
    image_dir = build_image_context(REPO_ROOT, out, manifest)

    dockerfile = open(os.path.join(image_dir, "Dockerfile")).read()
    assert 'ENTRYPOINT ["python", "-m", "tf_operator_tpu.cli.operator"]' in dockerfile
    ctx = os.path.join(image_dir, "context")
    # COPY paths in the Dockerfile must exist in the staged context.
    for rel in ("tf_operator_tpu", "examples", "bench.py", "README.md"):
        assert os.path.exists(os.path.join(ctx, rel)), rel
    # The entrypoint actually runs from the context alone (no repo on path).
    proc = subprocess.run(
        [sys.executable, "-m", "tf_operator_tpu.cli.operator", "--version"],
        capture_output=True, text=True, timeout=60,
        env={**os.environ, "PYTHONPATH": ctx}, cwd=str(tmp_path),
    )
    assert proc.returncode == 0 and "tpu-job-operator" in proc.stdout


# ---------------------------------------------------------------------------
# checks
# ---------------------------------------------------------------------------


def test_checks_flag_syntax_and_unused_imports(tmp_path):
    (tmp_path / "bad.py").write_text("def broken(:\n")
    (tmp_path / "unused.py").write_text("import os\nimport sys\nprint(sys.path)\n")
    (tmp_path / "clean.py").write_text("import os\nprint(os.getcwd())\n")
    problems = run_checks(("bad.py", "unused.py", "clean.py"), str(tmp_path))
    msgs = {p.message for p in problems}
    assert any("syntax error" in m for m in msgs)
    assert "unused import: os" in msgs
    assert not any(p.path.endswith("clean.py") for p in problems)


def test_repo_passes_its_own_checks():
    assert run_checks(root=REPO_ROOT) == []


# ---------------------------------------------------------------------------
# workflow DAG
# ---------------------------------------------------------------------------


def _mark(ctx_log, name, fail=False, sleep=0.0):
    def action(ctx):
        if sleep:
            time.sleep(sleep)
        ctx_log.append(name)
        if fail:
            raise RuntimeError(f"{name} exploded")
    return action


def test_workflow_runs_dag_in_dependency_order(tmp_path):
    log = []
    wf = Workflow("t", [
        Step("a", _mark(log, "a")),
        Step("b", _mark(log, "b"), deps=("a",)),
        Step("c", _mark(log, "c"), deps=("a",)),
        Step("d", _mark(log, "d"), deps=("b", "c")),
    ])
    assert wf.run(str(tmp_path)) is True
    assert log[0] == "a" and log[-1] == "d" and set(log) == {"a", "b", "c", "d"}
    assert json.load(open(tmp_path / "finished.json"))["passed"] is True


def test_workflow_failure_skips_dependents_but_runs_always_steps(tmp_path):
    log = []
    wf = Workflow("t", [
        Step("ok", _mark(log, "ok")),
        Step("boom", _mark(log, "boom", fail=True), deps=("ok",)),
        Step("after", _mark(log, "after"), deps=("boom",)),
        Step("teardown", _mark(log, "teardown"), deps=("boom",), always=True),
    ])
    assert wf.run(str(tmp_path)) is False
    assert "after" not in log  # skipped
    assert "teardown" in log  # exit-handler semantics
    finished = json.load(open(tmp_path / "finished.json"))
    assert finished["metadata"] == {
        "ok": "passed", "boom": "failed", "after": "skipped",
        "teardown": "passed",
    }
    junit_xml = (tmp_path / "junit_t.xml").read_text()
    assert "boom exploded" in junit_xml


def test_workflow_subprocess_step_logs_and_exit_codes(tmp_path):
    import sys

    wf = Workflow("t", [
        Step("shout", [sys.executable, "-c", "print('hello from step')"]),
        Step("die", [sys.executable, "-c", "raise SystemExit(3)"]),
    ])
    assert wf.run(str(tmp_path)) is False
    assert "hello from step" in (tmp_path / "logs" / "shout.log").read_text()
    assert wf.results["die"].status == "failed"
    assert "exit code 3" in wf.results["die"].message


def test_workflow_parallel_branches_overlap(tmp_path):
    log = []
    t0 = time.monotonic()
    wf = Workflow("t", [
        Step("s1", _mark(log, "s1", sleep=0.5)),
        Step("s2", _mark(log, "s2", sleep=0.5)),
        Step("s3", _mark(log, "s3", sleep=0.5)),
    ])
    assert wf.run(str(tmp_path)) is True
    assert time.monotonic() - t0 < 1.2  # ran concurrently, not 1.5s serially


def test_workflow_rejects_bad_dags():
    with pytest.raises(ValueError, match="unknown dep"):
        Workflow("t", [Step("a", [], deps=("nope",))])
    with pytest.raises(ValueError, match="cycle"):
        Workflow("t", [
            Step("a", [], deps=("b",)),
            Step("b", [], deps=("a",)),
        ])
    with pytest.raises(ValueError, match="duplicate"):
        Workflow("t", [Step("a", []), Step("a", [])])


# ---------------------------------------------------------------------------
# the full default E2E workflow against a real operator (integration)
# ---------------------------------------------------------------------------


def test_default_e2e_workflow_end_to_end(tmp_path):
    from tf_operator_tpu.harness.workflow import default_e2e_workflow

    wf = default_e2e_workflow(
        unit_tests=("tests/test_utils.py",), e2e_workers=2, e2e_trials=1
    )
    ok = wf.run(str(tmp_path))
    statuses = {n: r.status for n, r in wf.results.items()}
    assert ok, (statuses, _tail_logs(tmp_path))
    assert statuses == {
        "build": "passed", "unit": "passed", "deploy": "passed",
        "e2e": "passed", "teardown": "passed",
    }
    assert (tmp_path / "dist" / "manifest.json").exists()
    assert (tmp_path / "junit_e2e_suite.xml").exists()
    assert json.load(open(tmp_path / "finished.json"))["passed"] is True


def _tail_logs(tmp_path):
    out = {}
    logs = tmp_path / "logs"
    if logs.is_dir():
        for f in logs.iterdir():
            out[f.name] = f.read_text()[-2000:]
    return out


def test_workflow_callable_step_timeout(tmp_path):
    def hang(ctx):
        time.sleep(30)

    wf = Workflow("t", [Step("hang", hang, timeout=0.5)])
    t0 = time.monotonic()
    assert wf.run(str(tmp_path)) is False
    assert time.monotonic() - t0 < 5
    assert "timeout" in wf.results["hang"].message.lower()
