"""Release builder, prow artifacts, checks, deploy, and the workflow DAG.

Parity targets: py/release.py + build_and_push_image.py (content-tagged
artifacts), py/prow.py (started/finished contract), py/py_checks.py (lint
gate), py/deploy.py (operator up/down), and the Argo E2E DAG
(workflows.libsonnet topology semantics)."""

import json
import os
import tarfile
import time

import pytest

from tf_operator_tpu.harness import prow
from tf_operator_tpu.harness.checks import run_checks
from tf_operator_tpu.harness.workflow import Step, Workflow
from tf_operator_tpu.release.build import build_release

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# ---------------------------------------------------------------------------
# prow artifacts
# ---------------------------------------------------------------------------


def test_prow_started_finished(tmp_path):
    d = str(tmp_path)
    started = prow.create_started(d, repo="org/tpu-operator", pull="123",
                                  repo_root=REPO_ROOT, now=1000)
    assert started["timestamp"] == 1000
    assert started["repos"] == {"org/tpu-operator": "123"}
    assert len(started["repo-version"]) == 40  # a real git sha

    finished = prow.create_finished(d, False, {"e2e": "failed"}, now=2000)
    assert finished["result"] == "FAILURE" and not finished["passed"]

    on_disk = json.load(open(tmp_path / "finished.json"))
    assert on_disk["metadata"] == {"e2e": "failed"}
    assert json.load(open(tmp_path / "started.json"))["timestamp"] == 1000


# ---------------------------------------------------------------------------
# release build
# ---------------------------------------------------------------------------


def test_release_build_manifest_and_tarball(tmp_path):
    out = str(tmp_path / "dist")
    manifest = build_release(REPO_ROOT, out)
    assert manifest["git_sha"] != "unknown"
    assert manifest["name"].startswith("tpu-operator-0.")
    tar_path = os.path.join(out, manifest["artifact"])
    with tarfile.open(tar_path) as tar:
        names = tar.getnames()
    root = manifest["name"]
    assert f"{root}/tf_operator_tpu/version.py" in names
    assert f"{root}/bench.py" in names
    assert all(n.startswith(root + "/") for n in names)

    # content digest is deterministic across rebuilds
    manifest2 = build_release(REPO_ROOT, str(tmp_path / "dist2"))
    assert manifest2["content_digest"] == manifest["content_digest"]


def test_release_image_context_is_runnable(tmp_path):
    """--image-context stages Dockerfile + flattened sources such that the
    image ENTRYPOINT module resolves from the staged context (parity:
    build/images/tf_operator/Dockerfile builds operators+dashboard into one
    image)."""
    import subprocess
    import sys

    from tf_operator_tpu.release.build import build_image_context

    out = str(tmp_path / "dist")
    manifest = build_release(REPO_ROOT, out)
    image_dir = build_image_context(REPO_ROOT, out, manifest)

    dockerfile = open(os.path.join(image_dir, "Dockerfile")).read()
    assert 'ENTRYPOINT ["python", "-m", "tf_operator_tpu.cli.operator"]' in dockerfile
    ctx = os.path.join(image_dir, "context")
    # COPY paths in the Dockerfile must exist in the staged context.
    for rel in ("tf_operator_tpu", "examples", "bench.py", "README.md"):
        assert os.path.exists(os.path.join(ctx, rel)), rel
    # The entrypoint actually runs from the context alone (no repo on path).
    proc = subprocess.run(
        [sys.executable, "-m", "tf_operator_tpu.cli.operator", "--version"],
        capture_output=True, text=True, timeout=60,
        env={**os.environ, "PYTHONPATH": ctx}, cwd=str(tmp_path),
    )
    assert proc.returncode == 0 and "tpu-job-operator" in proc.stdout


# ---------------------------------------------------------------------------
# OCI image build + registry push (py/build_and_push_image.py parity)
# ---------------------------------------------------------------------------


def _tiny_context(tmp_path):
    ctx = tmp_path / "ctx"
    (ctx / "pkg").mkdir(parents=True)
    (ctx / "pkg" / "__init__.py").write_text("VERSION = '1'\n")
    (ctx / "entry.py").write_text("print('hi')\n")
    return str(ctx)


def test_oci_image_is_deterministic_and_wellformed(tmp_path):
    import gzip as gzip_mod
    import hashlib
    import io
    import json as json_mod
    import tarfile as tarfile_mod

    from tf_operator_tpu.release import oci

    ctx = _tiny_context(tmp_path)
    img1 = oci.build_image(ctx, labels={"l": "v"})
    img2 = oci.build_image(ctx, labels={"l": "v"})
    assert img1.manifest_digest == img2.manifest_digest  # reproducible
    assert img1.layer_digest == (
        "sha256:" + hashlib.sha256(img1.layer).hexdigest()
    )
    raw = gzip_mod.decompress(img1.layer)
    assert img1.diff_id == "sha256:" + hashlib.sha256(raw).hexdigest()
    names = tarfile_mod.open(fileobj=io.BytesIO(raw)).getnames()
    assert "opt/tpu-operator/pkg/__init__.py" in names
    manifest = json_mod.loads(img1.manifest)
    assert manifest["config"]["digest"] == img1.config_digest
    assert manifest["layers"][0]["size"] == len(img1.layer)
    config = json_mod.loads(img1.config)
    assert config["rootfs"]["diff_ids"] == [img1.diff_id]
    assert config["config"]["Entrypoint"][:3] == [
        "python", "-m", "tf_operator_tpu.cli.operator"
    ]


def test_push_to_registry_stub_and_pull_roundtrip(tmp_path):
    from tf_operator_tpu.release import oci
    from tf_operator_tpu.release.registry_stub import RegistryStub

    stub = RegistryStub()
    stub.start()
    try:
        img = oci.build_image(_tiny_context(tmp_path))
        pushed = oci.push_image(
            img, stub.url, "tpu-operator", ["v1-g123", "abc123", "latest"]
        )
        assert pushed["digest"] == img.manifest_digest
        host = stub.url.split("://", 1)[1]
        assert pushed["ref"] == f"{host}/tpu-operator@{img.manifest_digest}"
        # Pull back by tag AND by digest; bytes must round-trip exactly so
        # the digest pin stays valid.
        client = oci.RegistryClient(stub.url)
        for ref in ("latest", img.manifest_digest):
            body, digest = client.get_manifest("tpu-operator", ref)
            assert body == img.manifest and digest == img.manifest_digest
        assert client.has_blob("tpu-operator", img.layer_digest)
        assert client.has_blob("tpu-operator", img.config_digest)
        # Second push: blobs dedup (HEAD hit), manifests re-tag idempotently.
        oci.push_image(img, stub.url, "tpu-operator", ["latest"])
        import urllib.request

        tags = json.load(
            urllib.request.urlopen(stub.url + "/v2/tpu-operator/tags/list")
        )
        assert set(tags["tags"]) == {"v1-g123", "abc123", "latest"}
    finally:
        stub.stop()


def test_registry_rejects_bad_digest_and_orphan_manifest(tmp_path):
    from tf_operator_tpu.release import oci
    from tf_operator_tpu.release.registry_stub import RegistryStub

    stub = RegistryStub()
    stub.start()
    try:
        img = oci.build_image(_tiny_context(tmp_path))
        client = oci.RegistryClient(stub.url)
        # Upload with a lying digest: registry must verify and refuse.
        with pytest.raises(oci.RegistryError, match="upload"):
            client.upload_blob(
                "r", "sha256:" + "0" * 64, b"not-that-content"
            )
        # Manifest referencing never-pushed blobs: refused (the blobs-
        # before-manifest ordering real registries enforce).
        with pytest.raises(oci.RegistryError, match="manifest PUT"):
            client.put_manifest("r", "latest", img)
    finally:
        stub.stop()


def test_release_cli_pushes_and_deploy_consumes_ref(tmp_path):
    """End-to-end release: build → push to a local registry → the manifest
    carries a digest-pinned ref that kube-up templating stamps into
    deploy/operator.yaml (py/release.py:123,249 + deploy consumption)."""
    from tf_operator_tpu.harness.deploy import _render_operator_manifest
    from tf_operator_tpu.release.build import main as release_main
    from tf_operator_tpu.release.registry_stub import RegistryStub

    stub = RegistryStub()
    stub.start()
    try:
        out = str(tmp_path / "dist")
        rc = release_main([
            "--out", out, "--registry", stub.url, "--oci-layout",
        ])
        assert rc == 0
        manifest = json.load(open(os.path.join(out, "manifest.json")))
        push = manifest["push"]
        assert push["digest"].startswith("sha256:")
        assert manifest["git_sha"] in push["tags"]
        assert "latest" in push["tags"]
        # OCI layout on disk next to the tarball.
        layout = manifest["oci_layout"]
        assert json.load(open(os.path.join(layout, "oci-layout")))[
            "imageLayoutVersion"
        ] == "1.0.0"
        index = json.load(open(os.path.join(layout, "index.json")))
        assert {
            m["annotations"]["org.opencontainers.image.ref.name"]
            for m in index["manifests"]
        } == set(push["tags"])
        blob_dir = os.path.join(layout, "blobs", "sha256")
        assert len(os.listdir(blob_dir)) == 3  # layer + config + manifest
        # Deploy templating pins the pushed, immutable ref.
        doc = _render_operator_manifest("prod", image=push["ref"])
        assert f"image: {push['ref']}" in doc
        assert "image: tpu-operator:latest" not in doc
    finally:
        stub.stop()


def test_bundle_roundtrip_build_render_deploy(tmp_path):
    """Versioned deploy bundle (helm-chart analog, py/release.py:54-70):
    release build emits the bundle, values render strictly, and kube-up
    consumes the tarball directly — applying the RENDERED docs (namespace,
    image, replicas, resources all from values) in the right order."""
    import yaml

    from tf_operator_tpu.harness.deploy import kubectl_deploy
    from tf_operator_tpu.release.build import main as release_main
    from tf_operator_tpu.release.bundle import load_bundle, render

    out = str(tmp_path / "dist")
    assert release_main(["--out", out]) == 0
    manifest = json.load(open(os.path.join(out, "manifest.json")))
    bundle_path = os.path.join(out, manifest["bundle"])
    assert manifest["bundle_name"].startswith("tpu-operator-bundle-")

    # Deterministic: rebuilding produces byte-identical bundles.
    out2 = str(tmp_path / "dist2")
    assert release_main(["--out", out2]) == 0
    assert (
        open(bundle_path, "rb").read()
        == open(os.path.join(out2, manifest["bundle"]), "rb").read()
    )

    bundle = load_bundle(bundle_path)
    assert bundle["meta"]["version"] == manifest["version"]
    assert bundle["meta"]["git_sha"] == manifest["git_sha"]

    # Strict rendering: unknown keys and undeclared placeholders error.
    with pytest.raises(ValueError, match="unknown value"):
        render(bundle, {"no_such_key": 1})
    docs = render(bundle, {
        "namespace": "prod", "image": "reg.example/tpu-operator@sha256:abc",
        "replicas": 2, "memory_limit": "2Gi",
    })
    rendered = list(yaml.safe_load_all(docs["operator.yaml"]))
    dep = next(d for d in rendered if d["kind"] == "Deployment")
    assert dep["metadata"]["namespace"] == "prod"
    assert dep["spec"]["replicas"] == 2
    ctr = dep["spec"]["template"]["spec"]["containers"][0]
    assert ctr["image"] == "reg.example/tpu-operator@sha256:abc"
    assert ctr["resources"]["limits"]["memory"] == "2Gi"
    assert ctr["resources"]["requests"]["cpu"] == "100m"
    assert "{{" not in docs["operator.yaml"] and "{{" not in docs["crd.yaml"]
    # CRD ships verbatim.
    crd = yaml.safe_load(docs["crd.yaml"])
    assert crd["kind"] == "CustomResourceDefinition"

    # kube-up consumes the bundle: every doc applied comes from the
    # rendered templates (no repo deploy/ files), namespace first, CRD
    # before the operator.
    applied: list[tuple[list, bytes | None]] = []

    class _OK:
        returncode = 0

    def recorder(cmd, **kw):
        applied.append((cmd, kw.get("input")))
        return _OK()

    ran = kubectl_deploy(
        "apply", namespace="prod", bundle=bundle_path, runner=recorder,
    )
    assert all("-f" not in cmd or "deploy/" not in " ".join(cmd)
               for cmd, _ in applied)
    stdin_docs = [inp.decode() for _, inp in applied if inp]
    assert any("kind: Namespace" in d for d in stdin_docs)
    # CRD rendered doc applied before the operator doc.
    crd_idx = next(i for i, d in enumerate(stdin_docs)
                   if "CustomResourceDefinition" in d)
    op_idx = next(i for i, d in enumerate(stdin_docs)
                  if "kind: Deployment" in d)
    assert crd_idx < op_idx
    # The operator doc carries the overridden namespace and the bundle's
    # default image value (no --image passed here).
    assert "namespace: prod" in stdin_docs[op_idx]
    assert "image: tpu-operator:latest" in stdin_docs[op_idx]
    assert len(ran) >= 4  # ns, secret probe(+create), crd, operator


# ---------------------------------------------------------------------------
# checks
# ---------------------------------------------------------------------------


def test_checks_flag_syntax_and_unused_imports(tmp_path):
    (tmp_path / "bad.py").write_text("def broken(:\n")
    (tmp_path / "unused.py").write_text("import os\nimport sys\nprint(sys.path)\n")
    (tmp_path / "clean.py").write_text("import os\nprint(os.getcwd())\n")
    problems = run_checks(("bad.py", "unused.py", "clean.py"), str(tmp_path))
    msgs = {p.message for p in problems}
    assert any("syntax error" in m for m in msgs)
    assert "unused import: os" in msgs
    assert not any(p.path.endswith("clean.py") for p in problems)


def test_repo_passes_its_own_checks():
    assert run_checks(root=REPO_ROOT) == []


# ---------------------------------------------------------------------------
# workflow DAG
# ---------------------------------------------------------------------------


def _mark(ctx_log, name, fail=False, sleep=0.0):
    def action(ctx):
        if sleep:
            time.sleep(sleep)
        ctx_log.append(name)
        if fail:
            raise RuntimeError(f"{name} exploded")
    return action


def test_workflow_runs_dag_in_dependency_order(tmp_path):
    log = []
    wf = Workflow("t", [
        Step("a", _mark(log, "a")),
        Step("b", _mark(log, "b"), deps=("a",)),
        Step("c", _mark(log, "c"), deps=("a",)),
        Step("d", _mark(log, "d"), deps=("b", "c")),
    ])
    assert wf.run(str(tmp_path)) is True
    assert log[0] == "a" and log[-1] == "d" and set(log) == {"a", "b", "c", "d"}
    assert json.load(open(tmp_path / "finished.json"))["passed"] is True


def test_workflow_failure_skips_dependents_but_runs_always_steps(tmp_path):
    log = []
    wf = Workflow("t", [
        Step("ok", _mark(log, "ok")),
        Step("boom", _mark(log, "boom", fail=True), deps=("ok",)),
        Step("after", _mark(log, "after"), deps=("boom",)),
        Step("teardown", _mark(log, "teardown"), deps=("boom",), always=True),
    ])
    assert wf.run(str(tmp_path)) is False
    assert "after" not in log  # skipped
    assert "teardown" in log  # exit-handler semantics
    finished = json.load(open(tmp_path / "finished.json"))
    assert finished["metadata"] == {
        "ok": "passed", "boom": "failed", "after": "skipped",
        "teardown": "passed",
    }
    junit_xml = (tmp_path / "junit_t.xml").read_text()
    assert "boom exploded" in junit_xml


def test_workflow_subprocess_step_logs_and_exit_codes(tmp_path):
    import sys

    wf = Workflow("t", [
        Step("shout", [sys.executable, "-c", "print('hello from step')"]),
        Step("die", [sys.executable, "-c", "raise SystemExit(3)"]),
    ])
    assert wf.run(str(tmp_path)) is False
    assert "hello from step" in (tmp_path / "logs" / "shout.log").read_text()
    assert wf.results["die"].status == "failed"
    assert "exit code 3" in wf.results["die"].message


def test_workflow_parallel_branches_overlap(tmp_path):
    log = []
    t0 = time.monotonic()
    wf = Workflow("t", [
        Step("s1", _mark(log, "s1", sleep=0.5)),
        Step("s2", _mark(log, "s2", sleep=0.5)),
        Step("s3", _mark(log, "s3", sleep=0.5)),
    ])
    assert wf.run(str(tmp_path)) is True
    assert time.monotonic() - t0 < 1.2  # ran concurrently, not 1.5s serially


def test_workflow_rejects_bad_dags():
    with pytest.raises(ValueError, match="unknown dep"):
        Workflow("t", [Step("a", [], deps=("nope",))])
    with pytest.raises(ValueError, match="cycle"):
        Workflow("t", [
            Step("a", [], deps=("b",)),
            Step("b", [], deps=("a",)),
        ])
    with pytest.raises(ValueError, match="duplicate"):
        Workflow("t", [Step("a", []), Step("a", [])])


# ---------------------------------------------------------------------------
# the full default E2E workflow against a real operator (integration)
# ---------------------------------------------------------------------------


def test_default_e2e_workflow_end_to_end(tmp_path):
    from tf_operator_tpu.harness.workflow import default_e2e_workflow

    wf = default_e2e_workflow(
        unit_tests=("tests/test_utils.py",), e2e_workers=2, e2e_trials=1
    )
    ok = wf.run(str(tmp_path))
    statuses = {n: r.status for n, r in wf.results.items()}
    assert ok, (statuses, _tail_logs(tmp_path))
    assert statuses == {
        "build": "passed", "unit": "passed", "deploy": "passed",
        "e2e": "passed", "realcluster": "passed", "teardown": "passed",
    }
    assert (tmp_path / "dist" / "manifest.json").exists()
    assert (tmp_path / "junit_e2e_suite.xml").exists()
    assert json.load(open(tmp_path / "finished.json"))["passed"] is True


def test_realcluster_stage_skips_cleanly_without_cluster(tmp_path, monkeypatch):
    """The optional real-apiserver stage (reference parity: prow CI runs
    on a live cluster) must be skipped-not-broken when no cluster is
    configured: it PASSES and records an explicit skip reason, so the day
    TPUFLOW_E2E_KUBECONFIG exists nothing new needs writing."""
    from tf_operator_tpu.harness.workflow import default_e2e_workflow

    monkeypatch.delenv("TPUFLOW_E2E_KUBECONFIG", raising=False)
    wf = default_e2e_workflow()
    step = wf.steps["realcluster"]
    ctx = {"artifacts_dir": str(tmp_path), "env": {}, "outputs": {}}
    step.action(ctx)  # must not raise
    assert "skipped" in ctx["outputs"]["realcluster"]
    assert "TPUFLOW_E2E_KUBECONFIG" in ctx["outputs"]["realcluster"]


def test_realcluster_stage_fails_loudly_on_unreachable_cluster(
    tmp_path, monkeypatch
):
    """A CLAIMED cluster that doesn't work must FAIL the stage (not
    silently skip): point the kubeconfig at a nonexistent file and the
    underlying smoke errors out."""
    from tf_operator_tpu.harness.workflow import default_e2e_workflow

    monkeypatch.setenv(
        "TPUFLOW_E2E_KUBECONFIG", str(tmp_path / "no-such-kubeconfig")
    )
    (tmp_path / "logs").mkdir()
    wf = default_e2e_workflow()
    step = wf.steps["realcluster"]
    ctx = {"artifacts_dir": str(tmp_path), "env": {}, "outputs": {}}
    with pytest.raises(RuntimeError, match="real-apiserver smoke failed"):
        step.action(ctx)


def _tail_logs(tmp_path):
    out = {}
    logs = tmp_path / "logs"
    if logs.is_dir():
        for f in logs.iterdir():
            out[f.name] = f.read_text()[-2000:]
    return out


def test_workflow_callable_step_timeout(tmp_path):
    def hang(ctx):
        time.sleep(30)

    wf = Workflow("t", [Step("hang", hang, timeout=0.5)])
    t0 = time.monotonic()
    assert wf.run(str(tmp_path)) is False
    assert time.monotonic() - t0 < 5
    assert "timeout" in wf.results["hang"].message.lower()


# ---------------------------------------------------------------------------
# bench structure (smoke shapes through the production subprocess runner)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_bench_smoke_isolated_sections():
    """bench.py's per-section subprocess isolation emits every metric line
    and re-emits the flagship ResNet line last (the driver parses the last
    JSON line; a tunnel death mid-bench must cost at most one section)."""
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "bench.py")],
        env=dict(os.environ, BENCH_SMOKE="1", BENCH_SMOKE_ISOLATED="1"),
        capture_output=True, timeout=900, text=True,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [json.loads(l) for l in proc.stdout.splitlines()
             if l.startswith("{")]
    metrics = [l["metric"] for l in lines]
    assert metrics[-1].startswith("resnet50_train_images_per_sec"), metrics
    for want in ("tpujob_submit_to_all_running_median_ms",
                 "flash_attention_fwd_bwd_tflops",
                 "transformer_lm_tokens_per_sec",
                 "lm_decode_gen_tokens_per_sec",
                 "resnet50_train_images_per_sec"):
        assert any(m.startswith(want) for m in metrics), (want, metrics)
    for line in lines:
        assert {"metric", "value", "unit", "vs_baseline"} <= set(line)


def test_pyproject_metadata_consistent():
    """Packaging metadata: every console-script entry point resolves to a
    callable, the dynamic version attribute exists, and the package
    discovery pattern matches the real package name."""
    import importlib

    try:
        import tomllib  # 3.11+ stdlib
    except ImportError:
        import tomli as tomllib  # 3.10: the identical backport

    with open(os.path.join(REPO_ROOT, "pyproject.toml"), "rb") as f:
        meta = tomllib.load(f)
    for script, target in meta["project"]["scripts"].items():
        mod_name, attr = target.split(":")
        mod = importlib.import_module(mod_name)
        assert callable(getattr(mod, attr)), script
    ver_attr = meta["tool"]["setuptools"]["dynamic"]["version"]["attr"]
    mod_name, attr = ver_attr.rsplit(".", 1)
    assert getattr(importlib.import_module(mod_name), attr)
    assert any(
        pat.rstrip("*") == "tf_operator_tpu"
        for pat in meta["tool"]["setuptools"]["packages"]["find"]["include"]
    )
