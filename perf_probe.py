"""Perf decomposition probes — the bench-day triage tool.

`bench.py` produces the headline numbers; this script attributes a gap.
Each probe prints one JSON line; run all or pick with PROBE=name. Probes:

- ``h2d``: host→device bandwidth for a bench-shaped uint8 batch (the
  tunnel-transport roofline; images/sec ≤ bw / 150528 B).
- ``input``: host input pipeline standalone — loader-only and
  loader+augment images/sec (if this is below the achieved device rate,
  the chip is starved and nothing on-device will help).
- ``fwd_split``: ResNet fwd-only vs fwd+bwd step time (a bwd/fwd ratio
  far from ~2 points at gradient-path problems, e.g. dtype upcasts).
- ``stem``: ResNet img/s with conv7 vs s2d stem, synthetic device-resident
  input (isolates the MXU effect of the stem rewrite from input noise).
- ``synthetic``: ResNet img/s on device-resident synthetic data (the
  compute ceiling; the gap to bench.py's native-input number is the
  input+transfer cost).
- ``roofline``: the environment's MEASURED ceilings — jitted dispatch
  round trip, raw bf16 matmul TFLOP/s (single and scan-chained), and
  on-device copy bandwidth. Spec peaks assume local PCIe-attached
  chips; through a tunnel the real ceilings can sit far below spec
  (round 3 measured 111 TFLOP/s compute and 111 GB/s HBM on a chip
  whose spec says 197/819), so every MFU denominator should be checked
  against this probe, not the table.

Usage on hardware:   python perf_probe.py
Structure check:     BENCH_SMOKE=1 PROBE=input python perf_probe.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import bench  # noqa: E402 — reuse shapes/constants so probes match the bench


def emit(probe: str, **kw) -> None:
    print(json.dumps({"probe": probe, **{
        k: round(v, 3) if isinstance(v, float) else v for k, v in kw.items()
    }}), flush=True)


def timeit(fn, *args, reps: int = 5, per_rep_sync: bool = False) -> float:
    """Seconds per call: warm (compile) once, then time `reps` calls.

    per_rep_sync=True blocks after every call (latency measurements);
    otherwise calls are enqueued back-to-back and one final block
    measures throughput.
    """
    import jax

    jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    if per_rep_sync:
        for _ in range(reps):
            jax.block_until_ready(fn(*args))
    else:
        for _ in range(reps):
            out = fn(*args)
        jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def probe_h2d() -> None:
    import jax

    batch_bytes = bench.BATCH * bench.IMAGE_SIZE * bench.IMAGE_SIZE * 3
    x = np.random.default_rng(0).integers(
        0, 256, (bench.BATCH, bench.IMAGE_SIZE, bench.IMAGE_SIZE, 3), np.uint8
    )
    dt = timeit(jax.device_put, x, reps=10, per_rep_sync=True)
    gbps = batch_bytes / dt / 1e9
    emit(
        "h2d", gbps=gbps, ms_per_batch=dt * 1e3,
        images_per_sec_ceiling=bench.BATCH / dt,
    )


def probe_input() -> None:
    from tf_operator_tpu.native.augment import augment_records
    from tf_operator_tpu.native.pipeline import RecordPipeline

    path, record_size, rec_bytes = bench.ensure_bench_records()

    def run(with_augment: bool, n: int = 20) -> float:
        pipe = RecordPipeline(
            path, rec_bytes, bench.BATCH, prefetch=8, threads=4, seed=0,
            loop=True,
        )
        it = iter(pipe)
        next(it)  # warm
        count = 0
        t0 = time.perf_counter()
        for _ in range(n):
            raw = next(it)
            while raw.shape[0] < bench.BATCH:
                raw = np.concatenate([raw, next(it)])[: bench.BATCH]
            if with_augment:
                augment_records(
                    raw, (record_size, record_size, 3),
                    (bench.IMAGE_SIZE, bench.IMAGE_SIZE), seed=1,
                    index0=count, threads=8,
                )
            count += bench.BATCH
        dt = time.perf_counter() - t0
        pipe.close()
        return n * bench.BATCH / dt

    # The zero-copy path bench.py actually uses: mmap + gather-augment.
    from tf_operator_tpu.native.augment import augment_gather
    from tf_operator_tpu.native.pipeline import MMapRecordPipeline

    def run_mmap(n: int = 40) -> float:
        pipe = MMapRecordPipeline(
            path, rec_bytes, bench.BATCH, seed=0, loop=True
        )
        out = np.empty(
            (bench.BATCH, bench.IMAGE_SIZE, bench.IMAGE_SIZE, 3), np.uint8
        )
        count = 0
        pipe.next_indices()  # warm
        t0 = time.perf_counter()
        for _ in range(n):
            idx = pipe.next_indices()
            while len(idx) < bench.BATCH:
                idx = np.concatenate([idx, pipe.next_indices()])[: bench.BATCH]
            augment_gather(
                pipe.data, idx, rec_bytes,
                (record_size, record_size, 3),
                (bench.IMAGE_SIZE, bench.IMAGE_SIZE), seed=1,
                index0=count, threads=8, out=out,
            )
            pipe.labels(idx)
            count += bench.BATCH
        return n * bench.BATCH / (time.perf_counter() - t0)

    emit(
        "input",
        loader_images_per_sec=run(False),
        loader_augment_images_per_sec=run(True),
        mmap_gather_images_per_sec=run_mmap(),
        cpus=os.cpu_count(),
        loadavg_1m=os.getloadavg()[0],
    )


def _resnet_setup(stem: str | None = None, batch: int | None = None):
    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.resnet import resnet50
    from tf_operator_tpu.parallel.mesh import create_mesh
    from tf_operator_tpu.parallel.sharding import replicate
    from tf_operator_tpu.train.steps import (
        TrainState, make_classifier_train_step, sgd_momentum,
    )

    batch = batch or bench.BATCH
    mesh = create_mesh({"dp": len(jax.devices())}, jax.devices())
    stem = stem or os.environ.get("BENCH_STEM", "conv7")
    model = resnet50(dtype=jnp.bfloat16, stem=stem)
    x = jnp.zeros(
        (batch, bench.IMAGE_SIZE, bench.IMAGE_SIZE, 3), jnp.bfloat16
    )
    y = jnp.zeros((batch,), jnp.int32)
    variables = model.init(
        __import__("jax").random.PRNGKey(0), x, train=True
    )
    tx = sgd_momentum(0.1)
    state = replicate(
        mesh,
        TrainState.create(
            variables["params"], tx,
            batch_stats=variables.get("batch_stats"),
        ),
    )
    step = make_classifier_train_step(
        model, tx, mesh, has_batch_stats=True, donate=False
    )
    return mesh, model, state, step, {"image": x, "label": y}


def probe_fwd_split() -> None:
    import jax
    import jax.numpy as jnp

    mesh, model, state, step, batch = _resnet_setup()

    @jax.jit
    def fwd_only(state, batch):
        out, _ = model.apply(
            {"params": state.params, "batch_stats": state.batch_stats},
            batch["image"], train=True, mutable=["batch_stats"],
        )
        return jnp.mean(out)

    def timeit(fn, *args, reps=5):
        jax.block_until_ready(fn(*args))
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(*args)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / reps

    t_fwd = timeit(fwd_only, state, batch)
    t_full = timeit(lambda s, b: step(s, b)[0], state, batch)
    emit(
        "fwd_split", fwd_ms=t_fwd * 1e3, full_step_ms=t_full * 1e3,
        bwd_over_fwd=(t_full - t_fwd) / t_fwd if t_fwd else 0.0,
    )


def _synthetic_rate(stem: str, batch_size: int | None = None) -> float:
    from tf_operator_tpu.train.steps import fuse_steps

    batch_size = batch_size or bench.BATCH
    mesh, model, state, step, batch = _resnet_setup(stem, batch_size)
    fused = fuse_steps(step, bench.FUSED_STEPS, donate=False)
    state2, metrics = fused(state, batch)
    float(metrics["loss"])  # compile + complete
    t0 = time.perf_counter()
    for _ in range(bench.MEASURE_CALLS):
        state2, metrics = fused(state2, batch)
    float(metrics["loss"])
    dt = time.perf_counter() - t0
    return bench.MEASURE_CALLS * bench.FUSED_STEPS * batch_size / dt


def probe_synthetic() -> None:
    """Device-resident ResNet train rate at the bench batch AND at 2x
    batch (perf.md candidate: deeper MXU pipelines per conv at the cost
    of HBM) — run b256 first so a dying tunnel still answers the primary
    compute-vs-input split question."""
    stem = os.environ.get("BENCH_STEM", "conv7")
    base = _synthetic_rate(stem)
    results = {"images_per_sec": base}
    if not os.environ.get("BENCH_SMOKE"):
        try:
            results["images_per_sec_b2x"] = _synthetic_rate(
                stem, 2 * bench.BATCH
            )
        except Exception as exc:  # noqa: BLE001 — 2x batch may OOM
            results["b2x_error"] = repr(exc)[:120]
    emit("synthetic", **results)


def probe_stem() -> None:
    conv7 = _synthetic_rate("conv7")
    s2d = _synthetic_rate("s2d")
    emit(
        "stem", conv7_images_per_sec=conv7, s2d_images_per_sec=s2d,
        s2d_speedup=s2d / conv7 if conv7 else 0.0,
    )


def probe_flashramp() -> None:
    """Per-rep times for the 8k flash-attention config that measured a
    pathological 17.8 s/step on round-3 hardware (while 64k ran 10x
    faster with 16x the work). If later reps are fast, the earlier number
    was the intra-process throughput ramp; if uniformly slow, the 8k
    shape genuinely mis-tiles and the kernel needs work."""
    from tf_operator_tpu.ops import attention_kernel

    seq, batch = bench.smoke_attn_config()
    # warmup=0: the RAMP is the signal here — every rep timed from cold.
    rep_s = bench.attn_fwd_bwd_times(batch, seq, reps=8, warmup=0)
    emit(
        "flashramp", seq=seq, batch=batch,
        rep_seconds=[round(s, 4) for s in rep_s],
        best_tflops=bench.flash_model_flops(batch, seq) / min(rep_s[1:]) / 1e12,
        kernel=attention_kernel(seq, seq, bench.ATTN_HEAD_DIM, 2, causal=True),
    )


def probe_flashblocks() -> None:
    """A/B the decoupled flash-attention Q block on hardware: 8k causal
    fwd+bwd at block_q 256 (round-3 shipped behavior), 512 (the old
    auto-pick), and 1024 (the r05-measured winner, now MAX_Q_BLOCK)."""
    from tf_operator_tpu.ops.flash_attention import flash_attention

    seq, batch = bench.smoke_attn_config()
    interpret = bool(os.environ.get("BENCH_SMOKE"))
    q, k, v = bench.attn_inputs(batch, seq)
    results = {}
    for bq in (64, 128) if interpret else (256, 512, 1024):
        if seq % bq:
            continue

        call = bench.attn_fwd_bwd_call(
            lambda q, k, v, bq=bq: flash_attention(
                q, k, v, causal=True, block=64 if interpret else 256,
                block_q=bq, interpret=interpret),
            q, k, v,
        )
        dt = min(bench.timed_reps(call, reps=3, warmup=2))
        results[f"bq{bq}_tflops"] = (
            bench.flash_model_flops(batch, seq) / dt / 1e12
        )
    emit("flashblocks", seq=seq, batch=batch, **results)


def probe_qblock() -> None:
    """Settle the r05-window discrepancy: the direct flashblocks A/B
    measured bq1024 at 14.0 TFLOP/s while the ops.attention dispatch path
    (flashsweep/bench, same shape, same auto-picked blocks after the
    MAX_Q_BLOCK=1024 retune) read ~11.5. Interleave the two call paths
    and the explicit block sizes in ONE process, alternating rounds, so
    chip/tunnel drift between processes can't masquerade as a config
    effect. Reports best-rep TFLOP/s per leg + the auto-picked pair."""
    from tf_operator_tpu.ops import attention
    from tf_operator_tpu.ops.flash_attention import (
        flash_attention,
        select_block_pair,
    )

    seq, batch = bench.smoke_attn_config()
    interpret = bool(os.environ.get("BENCH_SMOKE"))
    q, k, v = bench.attn_inputs(batch, seq)
    flops = bench.flash_model_flops(batch, seq)

    def make_call(fn):
        # Shared construction with every other attention timing tool —
        # the whole point of this probe is comparability with them.
        return bench.attn_fwd_bwd_call(fn, q, k, v)

    legs = {"dispatch_auto": make_call(
        lambda q, k, v: attention(q, k, v, causal=True))}
    for bq in (64, 128) if interpret else (256, 512, 1024):
        if seq % bq == 0:
            legs[f"direct_bq{bq}"] = make_call(
                lambda q, k, v, bq=bq: flash_attention(
                    q, k, v, causal=True, block=64 if interpret else 256,
                    block_q=bq, interpret=interpret))

    for call in legs.values():  # compile + first-rep ramp, off the clock
        # slow-call early stop: on a degraded tunnel each call can run
        # minutes, and 2 unconditional warmups x 4 legs would eat the
        # whole stage budget before a single timed rep.
        bench._warm(call, warmup=2)
    best: dict[str, float] = {}
    for _ in range(4):  # interleaved rounds: drift hits every leg equally
        for name, call in legs.items():
            t0 = time.perf_counter()
            call()
            dt = time.perf_counter() - t0
            best[name] = min(best.get(name, float("inf")), dt)
    pair = select_block_pair(seq, seq, compiled=not interpret)
    emit(
        "qblock", seq=seq, batch=batch,
        auto_pair=list(pair) if pair else None,
        **{f"{name}_tflops": flops / dt / 1e12 for name, dt in best.items()},
    )


def probe_kvblock() -> None:
    """Paged-attention decode A/B + block-chunk geometry sweep (ISSUE
    18): the pallas kernel (ops/paged_attention.py) vs the gather
    oracle read, interleaved rounds in ONE process exactly like
    probe_qblock (chip/tunnel drift hits every leg equally), across
    kv_block sizes at a long context with lanes SPREAD over occupancy
    — the kernel's claim is per-lane-bounded HBM traffic, so the win
    must grow with the gap between mean lane length and max-S. Reports
    best-rep microseconds per decode step per leg plus the modeled
    KV-read fraction (pallas bytes / gather bytes — the roofline-level
    expectation the measured ratio should track on hardware; on a CPU
    smoke run the interpret-mode numbers are mechanism proof only)."""
    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.ops.paged_attention import paged_attend

    smoke = bool(os.environ.get("BENCH_SMOKE"))
    interpret = smoke
    if smoke:
        b, kv, g, dh, S = 2, 2, 2, 16, 64
        blks = (8, 16)
    else:
        # 12 MiB VMEM ceiling: S*kv*dh*(2+4) bytes of finalize scratch
        # — 4096 x 4 x 128 x 6 sits exactly at the budget.
        b, kv, g, dh, S = 8, 4, 8, 128, 4096
        blks = (64, 128, 256)
    h = kv * g
    dtype = jnp.float32 if smoke else jnp.bfloat16
    rng = np.random.default_rng(18)
    q = jnp.asarray(rng.standard_normal((b, 1, h, dh)), dtype)
    # Occupancy spread: one lane near max-S, the rest geometrically
    # shorter — mean length ~S/3, so gather reads ~3x the kernel's
    # model bytes per step.
    spread = [max(1, (S - 1) >> i) for i in range(b)]
    idx = jnp.asarray(spread, jnp.int32)

    legs = {}
    ratios = {}
    for blk in blks:
        table_len = S // blk
        nblk = [-(-(p + 1) // blk) for p in spread]
        nb = sum(nblk) + 1
        pool_k = jnp.asarray(
            rng.standard_normal((nb, blk, kv, dh)), dtype)
        pool_v = jnp.asarray(
            rng.standard_normal((nb, blk, kv, dh)), dtype)
        table = np.zeros((b, table_len), np.int32)
        nxt = 1
        for i in range(b):
            for e in range(nblk[i]):
                table[i, e] = nxt
                nxt += 1
        table = jnp.asarray(table)

        def gather_read(q, pk, pv, tbl, ix):
            # The oracle read: dense gather + batched einsums — what
            # _decode_attend_paged does under kv_attend="gather".
            keys = pk[tbl].reshape(b, S, kv, dh)
            vals = pv[tbl].reshape(b, S, kv, dh)
            qg = q.reshape(b, 1, kv, g, dh)
            s = jnp.einsum("bqkgd,bskd->bkgqs", qg, keys,
                           preferred_element_type=jnp.float32)
            s = s * (dh ** -0.5)
            valid = jnp.arange(S)[None, :] <= ix[:, None]
            s = jnp.where(valid[:, None, None, None, :], s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            out = jnp.einsum("bkgqs,bskd->bqkgd", p,
                             vals.astype(jnp.float32))
            return out.reshape(b, 1, h, dh)

        g_fn = jax.jit(gather_read)
        p_fn = jax.jit(lambda q, pk, pv, tbl, ix: paged_attend(
            q, pk, pv, tbl, ix, interpret=interpret))

        def make_call(fn, pk=pool_k, pv=pool_v, tbl=table):
            return lambda: jax.block_until_ready(fn(q, pk, pv, tbl, idx))

        legs[f"blk{blk}_gather"] = make_call(g_fn)
        legs[f"blk{blk}_pallas"] = make_call(p_fn)
        ratios[f"blk{blk}_kv_read_frac"] = (
            sum(nblk) * blk / (b * S)  # modeled pallas/gather KV bytes
        )

    for call in legs.values():  # compile off the clock
        bench._warm(call, warmup=2)
    best: dict[str, float] = {}
    for _ in range(4):  # interleaved rounds, same as probe_qblock
        for name, call in legs.items():
            t0 = time.perf_counter()
            call()
            dt = time.perf_counter() - t0
            best[name] = min(best.get(name, float("inf")), dt)
    emit(
        "kvblock", seq=S, batch=b, kv_heads=kv, head_dim=dh,
        interpret=interpret, mean_lane=sum(spread) / len(spread),
        **{f"{name}_us": dt * 1e6 for name, dt in best.items()},
        **ratios,
    )


def probe_flashsweep() -> None:
    """Best-rep attention TFLOP/s over a (seq, batch) grid: round 3's
    hardware sample showed 8k/b4 running 10x slower than 64k/b1 with 16x
    less work — this sweep separates a batch-dimension pathology from a
    sequence-length one (and from the warm-up ramp, since every cell gets
    multi-warmup best-rep timing)."""
    smoke = bool(os.environ.get("BENCH_SMOKE"))
    grid = (((256, 1), (256, 2)) if smoke
            else ((8192, 1), (8192, 4), (16384, 1), (16384, 2), (32768, 1)))
    results = {}
    for seq, batch in grid:
        dt = min(bench.attn_fwd_bwd_times(batch, seq))
        results[f"s{seq}_b{batch}_tflops"] = (
            bench.flash_model_flops(batch, seq) / dt / 1e12
        )
    emit("flashsweep", **results)


def probe_convsweep() -> None:
    """Per-shape conv rooflines — the HLO-level attribution for the ResNet
    collapse (VERDICT r3: 'if convs are slow through this backend, show it
    with an HLO-level probe'). Times each distinct ResNet-50 conv geometry
    as its own jitted op (fwd only, bf16, bench batch), reporting achieved
    TFLOP/s per shape. If the matmul roofline is healthy (111 TFLOP/s
    chained) but these convs are not, the backend's conv path — not the
    model, input, or transfer — owns the gap; a single slow outlier
    instead names the shape to rewrite (as the s2d stem did for conv7)."""
    import jax
    import jax.numpy as jnp

    smoke = bool(os.environ.get("BENCH_SMOKE"))
    batch = 8 if smoke else bench.BATCH
    # (label, H=W input, Cin, Cout, kernel, stride) — ResNet-50's distinct
    # conv classes at 224 input: the 7x7 stem, then each stage's 1x1
    # reduce / 3x3 spatial / 1x1 expand at its resolution.
    shapes = (
        ("stem7x7", 224, 3, 64, 7, 2),
        ("s1_3x3", 56, 64, 64, 3, 1),
        ("s1_1x1e", 56, 64, 256, 1, 1),
        ("s2_3x3", 28, 128, 128, 3, 1),
        ("s2_1x1e", 28, 128, 512, 1, 1),
        ("s3_3x3", 14, 256, 256, 3, 1),
        ("s3_1x1e", 14, 256, 1024, 1, 1),
        ("s4_3x3", 7, 512, 512, 3, 1),
        ("s4_1x1e", 7, 512, 2048, 1, 1),
    )
    if smoke:
        shapes = shapes[:2]
    results = {}
    for label, hw, cin, cout, k, stride in shapes:
        x = jax.random.normal(
            jax.random.PRNGKey(0), (batch, hw, hw, cin), jnp.bfloat16
        )
        w = jax.random.normal(
            jax.random.PRNGKey(1), (k, k, cin, cout), jnp.bfloat16
        )

        @jax.jit
        def conv(x, w, stride=stride):
            out = jax.lax.conv_general_dilated(
                x, w, (stride, stride), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                preferred_element_type=jnp.float32,
            )
            return out.astype(jnp.float32).sum()

        try:
            dt = min(bench.timed_reps(
                lambda: float(conv(x, w)), reps=3, warmup=2
            ))
        except Exception as exc:  # noqa: BLE001 — per-shape isolation
            results[f"{label}_error"] = repr(exc)[:120]
            continue
        out_hw = hw // stride
        flops = 2 * batch * out_hw * out_hw * k * k * cin * cout
        results[f"{label}_tflops"] = flops / dt / 1e12
    emit("convsweep", batch=batch, **results)


def probe_lmsweep() -> None:
    """MFU-vs-model-size curve (VERDICT r3 item 4): the 3.4%-MFU LM line
    came from a 176M-param model that may simply be too small to be
    compute-bound at batch 2; this sweep measures tokens/sec + MFU at
    ~176M / ~440M / ~840M params (same 8k seq) so the headline can move
    to the largest model if — and only if — the curve says the gap is a
    small-model artifact. Each size runs independently; an OOM at the
    largest size is reported, not fatal."""
    import jax

    smoke = bool(os.environ.get("BENCH_SMOKE"))
    seq = 256 if smoke else bench.LM_SEQ
    vocab = 256 if smoke else bench.LM_SIZE["vocab_size"]
    # (label, d_model, n_layers, d_ff, batch, remat)
    sizes = (
        (("tiny", 64, 2, 128, 2, False),) if smoke else (
            ("176M", 1024, 8, 4096, 2, False),
            ("440M", 1536, 12, 6144, 2, True),
            ("840M", 2048, 14, 8192, 1, True),
        )
    )
    peak = bench.chip_peak_tflops(jax.devices()[0])
    for label, d_model, n_layers, d_ff, B, remat in sizes:
        try:
            m = bench.lm_train_measure(
                d_model=d_model, n_layers=n_layers, d_ff=d_ff,
                batch=B, seq=seq, vocab_size=vocab, remat=remat,
                peak_tflops=peak,
            )
            emit(
                "lmsweep", size=label, batch=B, seq=seq, remat=remat,
                mfu_spec=m.pop("mfu"), **m,
            )
        except Exception as exc:  # noqa: BLE001 — per-size isolation
            emit("lmsweep", size=label, error=repr(exc)[:200])


def probe_decodesweep() -> None:
    """Steady-state decode throughput with ramp-aware timing (VERDICT r3
    item 5): round 3's 470-tok/s headline halved itself on warm-up ramp
    (steady_state said 940). More warmups + best-rep, at two batch sizes,
    reporting achieved HBM GB/s so the number lands directly against the
    measured (not spec) copy roofline."""
    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.transformer import (
        Transformer, TransformerConfig, generate,
    )

    from dataclasses import replace

    from tf_operator_tpu.models.transformer import quantize_decode_params

    smoke = bool(os.environ.get("BENCH_SMOKE"))
    B_list = (2,) if smoke else (8, 32)
    prompt_len = 8 if smoke else bench.DECODE_PROMPT
    steps = 8 if smoke else bench.DECODE_STEPS
    for B in B_list:
        total = prompt_len + steps
        cfg = TransformerConfig(
            dtype=jnp.bfloat16,
            **dict(bench.LM_SIZE, max_seq_len=total) if not smoke else dict(
                vocab_size=256, d_model=64, n_heads=4, n_layers=2, d_ff=128,
                max_seq_len=total),
        )
        model = Transformer(cfg)
        prompt = jnp.zeros((B, prompt_len), jnp.int32)
        params0 = model.init(jax.random.PRNGKey(0), prompt)["params"]
        params_bf16 = jax.tree.map(
            lambda x: x.astype(jnp.bfloat16), params0)
        # int8 leg: projection weights stored int8, dequantized in VMEM by
        # the Pallas kernel — the real decode-HBM optimization (the naive
        # XLA int8 path was rejected; docs/perf.md).
        kv_bf16 = bench.kv_cache_bytes(cfg, B, kv8=False)
        kv_int8 = bench.kv_cache_bytes(cfg, B, kv8=True)
        qparams = quantize_decode_params(params_bf16)
        variants = (
            ("bf16", cfg, params_bf16, kv_bf16),
            ("int8", replace(cfg, int8_decode=True), qparams, kv_bf16),
            # int8 KV cache: the cache-read half of the roofline (grows
            # with context while weights amortize over batch).
            ("kv8", replace(cfg, kv_int8=True), params_bf16, kv_int8),
            ("int8kv8", replace(cfg, int8_decode=True, kv_int8=True),
             qparams, kv_int8),
        )
        for label, vcfg, params, kv_bytes in variants:
            params_bytes = sum(
                x.size * x.dtype.itemsize for x in jax.tree.leaves(params))

            def call(vcfg=vcfg, params=params):
                out = generate(vcfg, params, prompt, num_steps=steps)
                int(out[0, -1])

            try:
                times = bench.timed_reps(call, reps=3, warmup=3)
            except Exception as exc:  # noqa: BLE001 — per-variant isolation
                emit("decodesweep", batch=B, weights=label,
                     error=repr(exc)[:200])
                continue
            dt = min(times)
            emit(
                "decodesweep", batch=B, weights=label,
                gen_tokens_per_sec=B * steps / dt,
                hbm_gbps=((params_bytes + kv_bytes) * steps + params_bytes)
                / dt / 1e9,
                mean_tokens_per_sec=B * steps / (sum(times) / len(times)),
                params_mb=params_bytes / 1e6,
            )


def probe_decodelong() -> None:
    """LONG-context decode A/B: bf16 cache vs int8 cache (kv_int8) at a
    context where the cache READ dominates the roofline. At the standard
    decodesweep shapes (256-token budget) the KV cache is ~17% of the
    per-step HBM read, so a cache-dtype change cannot move the headline;
    at 4k context with the same model the cache is ~75% of the read and
    kv_int8's halving should be directly visible in gen tok/s. Weights
    stay bf16 on both legs — this probe isolates the cache term the way
    decodesweep's int8 leg isolates the weight term."""
    import jax
    import jax.numpy as jnp

    from dataclasses import replace

    from tf_operator_tpu.models.transformer import (
        Transformer, TransformerConfig, generate,
    )

    smoke = bool(os.environ.get("BENCH_SMOKE"))
    B = 2 if smoke else 8
    prompt_len = 24 if smoke else 3968
    steps = 8 if smoke else 128
    total = prompt_len + steps
    cfg = TransformerConfig(
        dtype=jnp.bfloat16,
        **dict(bench.LM_SIZE, max_seq_len=total) if not smoke else dict(
            vocab_size=256, d_model=64, n_heads=4, n_layers=2, d_ff=128,
            max_seq_len=total),
    )
    model = Transformer(cfg)
    prompt = jnp.zeros((B, prompt_len), jnp.int32)
    params = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16),
        model.init(jax.random.PRNGKey(0), prompt)["params"],
    )
    params_bytes = sum(
        x.size * x.dtype.itemsize for x in jax.tree.leaves(params))
    # The full cache-reduction ladder: bf16 -> int8 cache (2x) -> GQA
    # (group-factor x) -> both multiplied. GQA legs re-init params (the
    # param tree differs); throughput comparisons stay valid because
    # decode is read-bound, not accuracy-bound, at matched shapes.
    gqa_kv = max(1, cfg.n_heads // 4)
    gcfg = replace(cfg, n_kv_heads=gqa_kv)
    gqa_model = Transformer(gcfg)
    gqa_params = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16),
        gqa_model.init(jax.random.PRNGKey(0), prompt)["params"],
    )
    gqa_bytes = sum(
        x.size * x.dtype.itemsize for x in jax.tree.leaves(gqa_params))
    variants = (
        ("bf16", cfg, params, params_bytes,
         bench.kv_cache_bytes(cfg, B, kv8=False)),
        ("kv8", replace(cfg, kv_int8=True), params, params_bytes,
         bench.kv_cache_bytes(cfg, B, kv8=True)),
        (f"gqa{gqa_kv}", gcfg, gqa_params, gqa_bytes,
         bench.kv_cache_bytes(gcfg, B, kv8=False)),
        (f"gqa{gqa_kv}kv8", replace(gcfg, kv_int8=True), gqa_params,
         gqa_bytes, bench.kv_cache_bytes(gcfg, B, kv8=True)),
    )
    for label, vcfg, vparams, params_bytes, kv_bytes in variants:
        def call(vcfg=vcfg, vparams=vparams):
            out = generate(vcfg, vparams, prompt, num_steps=steps)
            int(out[0, -1])

        try:
            times = bench.timed_reps(call, reps=3, warmup=3)
        except Exception as exc:  # noqa: BLE001 — per-variant isolation
            emit("decodelong", batch=B, context=total, cache=label,
                 error=repr(exc)[:200])
            continue
        dt = min(times)
        emit(
            "decodelong", batch=B, context=total, cache=label,
            gen_tokens_per_sec=B * steps / dt,
            hbm_gbps=((params_bytes + kv_bytes) * steps + params_bytes)
            / dt / 1e9,
            # mean vs best: the tunnel's intra-process ramp diagnostic
            # (same cross-check decodesweep carries).
            mean_tokens_per_sec=B * steps / (sum(times) / len(times)),
            kv_read_fraction=round(
                kv_bytes / (kv_bytes + params_bytes), 3),
            params_mb=params_bytes / 1e6,
        )


def run_window() -> None:
    """Hardware-window triage: run the probes that answer round 3's open
    questions, highest-value first, each in its own subprocess with a
    budget (a dying tunnel hangs inside native code; isolation bounds the
    damage to one probe). Usage: `python perf_probe.py window [budget_s]`.

    Order: roofline (is the chip in a fast or slow state right now?) →
    synthetic ResNet (device-resident compute rate — splits bench.py's
    59.9 img/s between compute and input/transfer) → flashramp (8k
    pathology: ramp or real) → flashblocks (Q-block A/B) → flashsweep
    (batch-vs-seq pathology grid) → stem (conv7 vs s2d decision) → h2d,
    then TWO bench LM legs (flash vs forced-xla attention, up to ~1100 s
    each) answering whether the flash kernel helps or hurts the LM step.
    Probe budget caps sum to ~4400 s; budget ~6600 s to guarantee both LM
    legs on a degraded chip (on a healthy one everything fits well inside
    the 3000 s default — each probe finishes far under its cap).
    """
    import subprocess

    me = os.path.abspath(__file__)
    total = float(sys.argv[2]) if len(sys.argv) > 2 else 3000.0
    deadline = time.monotonic() + total
    plan = [  # (probe, budget_s)
        ("roofline", 300.0),
        ("synthetic", 900.0),
        ("convsweep", 600.0),
        ("flashramp", 600.0),
        ("flashblocks", 600.0),
        ("flashsweep", 900.0),
        ("stem", 900.0),
        ("h2d", 180.0),
    ]
    def run_child(label: str, argv: list, env: dict, budget: float) -> None:
        try:
            proc = subprocess.run(argv, env=env, timeout=budget)
            if proc.returncode != 0:
                # A child dying instantly (jax init through a dead tunnel)
                # must be distinguishable from one that ran silently.
                print(f"window: {label} exited rc={proc.returncode}",
                      file=sys.stderr, flush=True)
        except subprocess.TimeoutExpired:
            print(f"window: {label} timed out after {budget:.0f}s",
                  file=sys.stderr, flush=True)

    for name, budget in plan:
        left = deadline - time.monotonic()
        if left < 60.0:
            print(f"window: out of budget before {name}", file=sys.stderr,
                  flush=True)
            break
        run_child(f"probe {name}", [sys.executable, me],
                  dict(os.environ, PROBE=name), min(budget, left))

    # LM kernel A/B: the bench LM section twice — flash dispatch (default)
    # vs TPU_OPERATOR_ATTN=xla forcing the XLA attention path. If round
    # 3's 8k-attention pathology is real (not the warm-up ramp), the xla
    # leg runs faster.
    bench_py = os.path.join(os.path.dirname(me), "bench.py")
    # Pin the knob on BOTH legs: an ambient TPU_OPERATOR_ATTN=xla export
    # would otherwise turn the flash leg into a second xla leg.
    for label, extra in (("lm-ab-flash", {"TPU_OPERATOR_ATTN": ""}),
                         ("lm-ab-xla", {"TPU_OPERATOR_ATTN": "xla"})):
        left = deadline - time.monotonic()
        if left < 60.0:
            print(f"window: out of budget before {label}", file=sys.stderr,
                  flush=True)
            break
        print(f"window: {label}", file=sys.stderr, flush=True)
        run_child(label, [sys.executable, bench_py, "--section", "lm"],
                  dict(os.environ, BENCH_WATCHDOG_S="0", **extra),
                  min(1100.0, left))


def probe_specdecode() -> None:
    """Speculative-decoding component costs on hardware (the two
    acceptance-curve ENDPOINTS that bound any trained draft/target pair;
    exactness itself is pinned CPU-side in tests/test_spec_decode.py):

    - ``plain``: target-only greedy generate (the baseline).
    - ``spec_self``: draft == target — 100% acceptance, k+1 tokens per
      round at FULL draft cost. Mechanics ceiling: isolates the chunked
      verify + rollback overhead from draft quality.
    - ``spec_cold``: a ~4x-smaller random draft — ~0% acceptance, 1
      token per round at maximal overhead. The floor.

    Speedup for a real pair with acceptance a and relative draft cost c:
    tokens/round = E[m]+1, round cost = (k+1)*c + chunk(k+1) target
    read; both components are measurable from these legs (chunk cost =
    spec_self round time minus k+1 draft steps)."""
    import jax
    import jax.numpy as jnp

    from tf_operator_tpu.models.spec_decode import speculative_generate
    from tf_operator_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
        generate,
    )

    B, prompt_len, steps = (
        bench.DECODE_BATCH, bench.DECODE_PROMPT, bench.DECODE_STEPS
    )
    k = 4
    cfg = TransformerConfig(
        dtype=jnp.bfloat16,
        **dict(bench.LM_SIZE, max_seq_len=prompt_len + steps + k + 1),
    )
    # ~4x fewer layers: the canonical cheap-draft shape (same width, so
    # embeddings/head stay compatible in spirit; params are random —
    # acceptance ~0 by construction, which is the point of the leg).
    draft_cfg = TransformerConfig(
        dtype=jnp.bfloat16,
        **dict(
            bench.LM_SIZE,
            n_layers=max(1, dict(bench.LM_SIZE)["n_layers"] // 4),
            max_seq_len=prompt_len + steps + k + 1,
        ),
    )
    prompt = jnp.zeros((B, prompt_len), jnp.int32)
    tparams = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16),
        Transformer(cfg).init(jax.random.PRNGKey(0), prompt)["params"],
    )
    dparams = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16),
        Transformer(draft_cfg).init(
            jax.random.PRNGKey(1), prompt
        )["params"],
    )

    def plain():
        int(generate(cfg, tparams, prompt, num_steps=steps)[0, -1])

    results = {}
    rounds: dict[str, int] = {}

    def leg(name, call):
        dt = min(bench.timed_reps(call, reps=2, warmup=2))
        results[f"tokens_per_sec_{name}"] = B * steps / dt

    leg("plain", plain)

    def segmented():
        # The streaming path (transformer.generate_segments): n_segments
        # host round-trips instead of one fused call — through a
        # dispatch-taxed tunnel this leg prices the streaming tax that
        # serve_lm's stream:true pays vs the one-shot decode above.
        # The segment is the largest power-of-two <= 16 DIVIDING steps:
        # zero last-segment overshoot, so the leg fits the cfg's k+1
        # margin at every DECODE_STEPS (a non-divisor segment overshoots
        # by up to segment-1 > k).
        from tf_operator_tpu.models.transformer import generate_segmented

        seg = next(s for s in (16, 8, 4, 2, 1) if steps % s == 0)
        int(generate_segmented(
            cfg, tparams, prompt, steps, segment=seg
        )[0, -1])

    leg("segmented", segmented)

    def spec(name, dcfg, dp):
        holder = {}

        def call():
            toks, r = speculative_generate(
                cfg, tparams, dcfg, dp, prompt, steps, k=k
            )
            int(toks[0, -1])
            holder["rounds"] = int(r)

        leg(name, call)
        rounds[name] = holder["rounds"]

    spec("spec_self", cfg, tparams)
    spec("spec_cold", draft_cfg, dparams)
    emit(
        "specdecode", batch=B, prompt_len=prompt_len, steps=steps, k=k,
        **results,
        rounds_self=rounds.get("spec_self"),
        rounds_cold=rounds.get("spec_cold"),
        tokens_per_round_self=steps / max(1, rounds.get("spec_self", 1)),
        tokens_per_round_cold=steps / max(1, rounds.get("spec_cold", 1)),
    )


def probe_roofline() -> None:
    import jax
    import jax.numpy as jnp

    smoke = bool(os.environ.get("BENCH_SMOKE"))

    # Dispatch round trip: a tiny jitted op, fully synchronized per rep.
    f = jax.jit(lambda x: x + 1)
    x = jnp.zeros((8,), jnp.float32)
    dispatch_ms = timeit(f, x, reps=20, per_rep_sync=True) * 1e3

    # Raw bf16 matmul across sizes: per-size single executables expose
    # size-dependent pathologies (round 3 observed 2048-cubed running 200x
    # slower than 8192-cubed through the tunnel); the scan chain amortizes
    # any per-executable overhead, so it is the compute ceiling.
    # Best-of-reps with multi-warmup, same as the chain/copy helpers — a
    # methodology mismatch here would confound the single-vs-chain gap
    # (per-executable overhead) with timing semantics.
    sizes = (512,) if smoke else (2048, 4096, 8192)
    single = {}
    for n in sizes:
        a = jax.random.normal(jax.random.PRNGKey(0), (n, n), jnp.bfloat16)
        b = jax.random.normal(jax.random.PRNGKey(1), (n, n), jnp.bfloat16)
        mm = jax.jit(lambda a, b: (a @ b).astype(jnp.float32).sum())
        dt = min(bench.timed_reps(lambda: float(mm(a, b)), reps=5, warmup=2))
        single[f"matmul_{n}_tflops"] = 2 * n**3 / dt / 1e12

    n = 512 if smoke else 4096
    chain_tflops = bench.measure_chain_matmul_tflops(n, 4 if smoke else 20)
    copy_gbps = bench.measure_copy_gbps()
    chain_copy_gbps = bench.measure_chain_copy_gbps()

    emit(
        "roofline",
        dispatch_roundtrip_ms=dispatch_ms,
        matmul_chain_tflops=chain_tflops,
        copy_gbps=copy_gbps,
        chain_copy_gbps=chain_copy_gbps,
        chain_n=n,
        device_kind=getattr(jax.devices()[0], "device_kind", "?"),
        **single,
    )


PROBES = {
    "roofline": probe_roofline,
    "flashramp": probe_flashramp,
    "flashblocks": probe_flashblocks,
    "qblock": probe_qblock,
    "kvblock": probe_kvblock,
    "flashsweep": probe_flashsweep,
    "h2d": probe_h2d,
    "input": probe_input,
    "fwd_split": probe_fwd_split,
    "synthetic": probe_synthetic,
    "stem": probe_stem,
    "convsweep": probe_convsweep,
    "lmsweep": probe_lmsweep,
    "decodesweep": probe_decodesweep,
    "decodelong": probe_decodelong,
    "specdecode": probe_specdecode,
}


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "window":
        run_window()
        return
    if os.environ.get("BENCH_SMOKE"):
        from tf_operator_tpu.parallel.testing import force_cpu_mesh

        force_cpu_mesh(1)
    only = os.environ.get("PROBE")
    for name, fn in PROBES.items():
        if only and name != only:
            continue
        try:
            fn()
        except Exception as exc:  # noqa: BLE001 — each probe independent
            print(f"probe {name} failed: {exc!r}", file=sys.stderr, flush=True)


if __name__ == "__main__":
    main()
